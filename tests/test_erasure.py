"""Erasure-coded shard placement (erasure/ + engine/store/wire/server).

Unit level: the GF(2^8) oracle's field algebra and any-k-of-n guarantee,
shard-container parsing and the per-shard digest that turns corruption
into *detection* (a poisoned shard is dropped, any k clean survivors
still reconstruct), byte-identical shard rebuilds, the batched device
kernel's bit-for-bit parity with the oracle, the placement schema's
shard_index column, the 13-byte shard ids on the wire, and the server's
min_peers spread (capped shares with a deep queue, greedy matching — the
exact pre-erasure behavior — with a shallow one).

System level: the striped chaos acceptance scenario — a client backs up
through the coordination server onto six storage peers as RS(4+2)
stripes; the local source tree is then DELETED; one holder dies and is
audit-demoted, and a single ``repair_round()`` rebuilds its shards from
the survivors (no source, no whole copy anywhere) onto a spare peer;
then a SECOND holder goes permanently dark and the restore still
reproduces the source byte-for-byte from the remaining any-4-of-6.
"""

import asyncio
import hashlib
import itertools
import random
import shutil
import time

import numpy as np
import pytest

from backuwup_tpu import defaults, wire
from backuwup_tpu.erasure import gf_cpu
from backuwup_tpu.erasure import stripe as rs_stripe
from backuwup_tpu.ops.backend import CpuBackend
from backuwup_tpu.ops.gear import CDCParams
from backuwup_tpu.store import Store
from backuwup_tpu.utils import faults
from backuwup_tpu.utils.faults import FaultPlane

BACKEND = CpuBackend(CDCParams.from_desired(4096))


@pytest.fixture
def loop():
    loop = asyncio.new_event_loop()
    yield loop
    loop.close()


@pytest.fixture
def plane():
    installed = faults.install(FaultPlane(seed=1234))
    yield installed
    faults.uninstall()


@pytest.fixture
def store(tmp_path):
    s = Store(tmp_path / "cfg", data_base=tmp_path / "data")
    s.set_obfuscation_key(b"\xaa\x01\x7f\x33")
    yield s
    s.close()


# --------------------------------------------------------------------------
# GF(2^8) oracle: field algebra
# --------------------------------------------------------------------------


def _slow_gf_mul(a: int, b: int) -> int:
    """Russian-peasant multiply mod 0x11d — independent of the tables."""
    out = 0
    while b:
        if b & 1:
            out ^= a
        a <<= 1
        if a & 0x100:
            a ^= 0x11D
        b >>= 1
    return out


def test_mul_table_matches_peasant_multiply(rng):
    for _ in range(500):
        a, b = rng.randrange(256), rng.randrange(256)
        assert gf_cpu.gf_mul(a, b) == _slow_gf_mul(a, b)


def test_gf_inverse_property():
    with pytest.raises(ZeroDivisionError):
        gf_cpu.gf_inv(0)
    for a in range(1, 256):
        assert gf_cpu.gf_mul(a, gf_cpu.gf_inv(a)) == 1


def test_generator_every_k_submatrix_invertible():
    # the any-k-of-n property IS this invertibility; check it exhaustively
    # for the production geometry
    k, m = defaults.RS_K, defaults.RS_M
    gen = gf_cpu.generator_matrix(k, m)
    assert np.array_equal(gen[:k], np.eye(k, dtype=np.uint8))  # systematic
    for rows in itertools.combinations(range(k + m), k):
        inv = gf_cpu.gf_invert_matrix(gen[list(rows)])
        prod = gf_cpu.gf_matmul(inv, gen[list(rows)])
        assert np.array_equal(prod, np.eye(k, dtype=np.uint8))


def test_generator_rejects_bad_geometry():
    with pytest.raises(ValueError):
        gf_cpu.generator_matrix(0, 2)
    with pytest.raises(ValueError):
        gf_cpu.generator_matrix(200, 100)


def test_reconstruct_rebuilds_exact_rows(nprng):
    k, m = 4, 2
    data = nprng.integers(0, 256, (k, 64), dtype=np.uint8)
    gen = gf_cpu.generator_matrix(k, m)
    shards = {i: gf_cpu.gf_matmul(gen[i:i + 1], data)[0]
              for i in range(k + m)}
    survivors = {i: shards[i] for i in (1, 3, 4, 5)}
    rebuilt = gf_cpu.reconstruct(survivors, k, m, missing=[0, 2])
    assert np.array_equal(rebuilt[0], shards[0])
    assert np.array_equal(rebuilt[2], shards[2])


# --------------------------------------------------------------------------
# stripe containers: any-k-of-n round trip + corruption detection
# --------------------------------------------------------------------------


@pytest.mark.parametrize("k,m", [(2, 1), (3, 2), (4, 2), (5, 3)])
def test_any_k_of_n_round_trip_every_subset(k, m, rng):
    data = rng.randbytes(k * 97 + 13)  # deliberately not a multiple of k
    containers = rs_stripe.split_packfile(data, k, m, BACKEND)
    assert len(containers) == k + m
    for subset in itertools.combinations(range(k + m), k):
        got = rs_stripe.assemble_packfile(
            [containers[i] for i in subset], BACKEND)
        assert got == data


@pytest.mark.parametrize("size", [0, 1, 4, 4 * 97])
def test_round_trip_edge_sizes(size, rng):
    data = rng.randbytes(size)
    containers = rs_stripe.split_packfile(data, 4, 2, BACKEND)
    assert rs_stripe.assemble_packfile(containers[2:], BACKEND) == data


def test_split_is_deterministic(rng):
    data = rng.randbytes(1000)
    assert rs_stripe.split_packfile(data, 4, 2, BACKEND) == \
        rs_stripe.split_packfile(data, 4, 2, BACKEND)


def test_corrupted_shard_detected_and_survived(rng):
    data = rng.randbytes(5000)
    containers = rs_stripe.split_packfile(data, 4, 2, BACKEND)
    bad = bytearray(containers[1])
    bad[rs_stripe.HEADER_LEN + 5] ^= 0xFF  # flip one payload byte
    bad = bytes(bad)
    shards, geom, drops = rs_stripe.collect_shards(
        [bad] + [containers[i] for i in (0, 2, 3, 4)], BACKEND)
    assert geom == (4, 2, len(data))
    assert 1 not in shards  # the poisoned shard never reaches the solve
    assert any("digest mismatch" in d for d in drops)
    # 4 clean survivors alongside the corrupt one: still reconstructs
    got = rs_stripe.assemble_packfile(
        [bad, containers[0], containers[2], containers[3], containers[4]],
        BACKEND)
    assert got == data
    # fewer than k clean shards: a hard error, not silent garbage
    with pytest.raises(rs_stripe.StripeError, match="need 4"):
        rs_stripe.assemble_packfile(
            [bad, containers[0], containers[2], containers[3]], BACKEND)


def test_parse_shard_rejects_malformed_containers(rng):
    data = rng.randbytes(256)
    good = rs_stripe.split_packfile(data, 2, 1, BACKEND)[0]
    with pytest.raises(rs_stripe.StripeError, match="not a shard"):
        rs_stripe.parse_shard(b"NOPE" + good[4:])
    with pytest.raises(rs_stripe.StripeError, match="version"):
        rs_stripe.parse_shard(good[:4] + bytes([99]) + good[5:])
    with pytest.raises(rs_stripe.StripeError, match="geometry"):
        rs_stripe.parse_shard(good[:6] + b"\x00" + good[7:])  # k = 0
    with pytest.raises(rs_stripe.StripeError, match="length mismatch"):
        rs_stripe.parse_shard(good + b"extra")


def test_shard_id_round_trip():
    pid = bytes(range(12))
    sid = rs_stripe.shard_id(pid, 5)
    assert len(sid) == wire.SHARD_ID_LEN
    assert rs_stripe.parse_shard_id(sid) == (pid, 5)
    with pytest.raises(rs_stripe.StripeError, match="length"):
        rs_stripe.parse_shard_id(pid)


def test_rebuild_shards_byte_identical(rng):
    # sourceless repair leans on this: a rebuilt container equals the
    # original bit-for-bit, so challenge tables stay valid and re-sends
    # to peers that already hold it are acked as idempotent duplicates
    data = rng.randbytes(3333)
    containers = rs_stripe.split_packfile(data, 4, 2, BACKEND)
    rebuilt = rs_stripe.rebuild_shards(
        [containers[i] for i in (1, 2, 4, 5)], [0, 3], BACKEND)
    assert rebuilt[0] == containers[0]
    assert rebuilt[3] == containers[3]
    with pytest.raises(rs_stripe.StripeError):
        rs_stripe.rebuild_shards(containers[:3], [4], BACKEND)  # < k left


def test_assemble_tree_reconstructs_and_reports(tmp_path, rng):
    from backuwup_tpu.snapshot.packfile import packfile_path

    data = rng.randbytes(4000)
    pid_ok, pid_bad = b"\x01" * 12, b"\x02" * 12
    containers = rs_stripe.split_packfile(data, 4, 2, BACKEND)
    shard_root = tmp_path / "shard"
    ok_dir = shard_root / pid_ok.hex()
    ok_dir.mkdir(parents=True)
    for i in (0, 2, 3, 5):  # any 4 of 6
        (ok_dir / f"{i:03d}").write_bytes(containers[i])
    bad_dir = shard_root / pid_bad.hex()
    bad_dir.mkdir(parents=True)
    for i in (0, 1):  # below k: must be reported, not crash the walk
        (bad_dir / f"{i:03d}").write_bytes(containers[i])
    done, failed = rs_stripe.assemble_tree(shard_root, tmp_path / "pack",
                                           BACKEND)
    assert done == [pid_ok]
    assert [pid for pid, _ in failed] == [pid_bad]
    assert packfile_path(tmp_path / "pack", pid_ok).read_bytes() == data


# --------------------------------------------------------------------------
# backend routing: CPU oracle vs batched kernel, bit for bit
# --------------------------------------------------------------------------


def test_cpu_backend_encode_decode_matches_oracle(nprng):
    k, m = 4, 2
    stripes = nprng.integers(0, 256, (3, k, 128), dtype=np.uint8)
    parity = BACKEND.encode_shards(stripes, m)
    expect = np.stack([gf_cpu.encode_stripe(s, m) for s in stripes])
    assert np.array_equal(parity, expect)
    full = np.concatenate([stripes, parity], axis=1)
    present = [0, 2, 4, 5]
    dec = BACKEND.decode_shards(full[:, present, :], k, m, present)
    assert np.array_equal(dec, stripes)


def test_device_kernel_matches_oracle_on_host(nprng):
    # rs_tpu's jit(vmap) table-gather kernel runs on whatever platform jax
    # is pinned to — under the tier-1 cpu pin this IS the parity check the
    # subsystem's ground truth demands (bit-for-bit vs the numpy oracle)
    from backuwup_tpu.erasure import rs_tpu

    k, m = defaults.RS_K, defaults.RS_M
    stripes = nprng.integers(0, 256, (4, k, 256), dtype=np.uint8)
    parity = np.asarray(rs_tpu.encode_stripes(stripes, m))
    expect = np.stack([gf_cpu.encode_stripe(s, m) for s in stripes])
    assert np.array_equal(parity, expect)
    full = np.concatenate([stripes, parity], axis=1)
    for present in itertools.combinations(range(k + m), k):
        dec = np.asarray(rs_tpu.decode_stripes(
            full[:, list(present), :], k, m, list(present)))
        assert np.array_equal(dec, stripes), f"survivors {present}"


@pytest.mark.accel
def test_device_kernel_matches_oracle_on_accelerator(nprng):
    # the same parity contract on real accelerator silicon, at a batch
    # size worth shipping to the device; auto-skipped under the tier-1
    # JAX_PLATFORMS=cpu pin by the conftest `accel` marker hook
    from backuwup_tpu.erasure import rs_tpu

    k, m = defaults.RS_K, defaults.RS_M
    stripes = nprng.integers(0, 256, (64, k, 4096), dtype=np.uint8)
    parity = np.asarray(rs_tpu.encode_stripes(stripes, m))
    expect = np.stack([gf_cpu.encode_stripe(s, m) for s in stripes])
    assert np.array_equal(parity, expect)
    present = list(range(m, k + m))
    full = np.concatenate([stripes, parity], axis=1)
    dec = np.asarray(rs_tpu.decode_stripes(
        full[:, present, :], k, m, present))
    assert np.array_equal(dec, stripes)


# --------------------------------------------------------------------------
# store: shard_index schema + deterministic peer ordering
# --------------------------------------------------------------------------


def test_store_shard_placement_round_trip(store):
    pid, pa, pb = b"\x0e" * 12, b"\x61" * 32, b"\x62" * 32
    store.record_placement(pid, pa, 100, shard_index=0)
    store.record_placement(pid, pb, 100, shard_index=1)
    # one shard per peer per stripe: the (pid, peer) key ignores the dup
    store.record_placement(pid, pa, 100, shard_index=2)
    assert store.shard_placements_for_peer(pa) == [(pid, 100, 0)]
    assert sorted(store.shards_for_packfile(pid)) == \
        sorted([(pa, 0), (pb, 1)])
    assert store.retire_placement(pid, pa) == 1
    assert store.shards_for_packfile(pid) == [(pb, 1)]
    assert store.retire_placement(pid, pa) == 0  # idempotent


def test_store_legacy_placement_reads_as_whole(store):
    pid, peer = b"\x0f" * 12, b"\x63" * 32
    store.record_placement(pid, peer, 500)  # pre-erasure call shape
    assert store.shard_placements_for_peer(peer) == [(pid, 500, -1)]
    assert store.shards_for_packfile(pid) == [(peer, -1)]


def test_find_peers_with_storage_tie_break_is_deterministic(store):
    hi, lo = b"\x02" * 32, b"\x01" * 32
    store.add_peer_negotiated(hi, 1000)
    store.add_peer_negotiated(lo, 1000)  # equal free space
    assert [p.pubkey for p in store.find_peers_with_storage()] == [lo, hi]


# --------------------------------------------------------------------------
# wire: 13-byte shard ids + geometry fields
# --------------------------------------------------------------------------


def test_shard_file_frame_round_trip():
    sid = rs_stripe.shard_id(b"\x07" * 12, 5)
    body = wire.P2PBody(
        kind=wire.P2PBodyKind.FILE,
        header=wire.P2PHeader(sequence_number=3,
                              session_nonce=b"\x01" * wire.TRANSPORT_NONCE_LEN),
        file_info=wire.FileInfoKind.SHARD, file_id=sid, data=b"container")
    out = wire.P2PBody.decode_bytes(body.encode_bytes())
    assert out.file_info == wire.FileInfoKind.SHARD
    assert out.file_id == sid and out.data == b"container"


def test_audit_ids_accept_shards_reject_other_lengths():
    sid = rs_stripe.shard_id(b"\x07" * 12, 0)
    c = wire.StorageChallenge(packfile_id=sid, offset=0, length=16,
                              nonce=b"\x00" * wire.AUDIT_NONCE_LEN)
    assert c.packfile_id == sid
    wire.StorageProof(packfile_id=b"\x07" * 12,
                      status=wire.ProofStatus.OK)  # legacy id still fine
    with pytest.raises(ValueError, match="12 or 13 bytes"):
        wire.StorageChallenge(packfile_id=b"\x07" * 11, offset=0, length=1,
                              nonce=b"\x00" * wire.AUDIT_NONCE_LEN)


def test_backup_request_min_peers_round_trip():
    msg = wire.BackupRequest(session_token=b"\x01" * 16,
                             storage_required=123, min_peers=6)
    out = wire.JsonMessage.from_json(msg.to_json())
    assert out.storage_required == 123 and out.min_peers == 6
    # pre-erasure senders omit the field: the default keeps them greedy
    assert wire.BackupRequest(session_token=b"\x01" * 16,
                              storage_required=1).min_peers == 1


def test_backup_restore_info_advertises_geometry():
    msg = wire.BackupRestoreInfo(snapshot_hash=b"\x0a" * 32,
                                 peers=["ff" * 32], rs_k=4, rs_m=2)
    out = wire.JsonMessage.from_json(msg.to_json())
    assert (out.rs_k, out.rs_m) == (4, 2)
    assert wire.BackupRestoreInfo().rs_k == 0  # pre-sharding servers


def test_engine_stripe_geometry_reads_defaults(monkeypatch):
    from backuwup_tpu.engine import Engine

    assert Engine._stripe_geometry() == (defaults.RS_K, defaults.RS_M)
    monkeypatch.setattr(defaults, "RS_M", 0)
    assert Engine._stripe_geometry() is None  # striping disabled entirely


# --------------------------------------------------------------------------
# server: min_peers spread in matchmaking
# --------------------------------------------------------------------------


class _AlwaysOnline:
    def is_online(self, client_id):
        return True

    async def notify(self, client_id, msg):
        return True


def _queue_with_candidates(candidates, each_bytes):
    from backuwup_tpu.net.server import ServerDB, StorageQueue

    db = ServerDB(":memory:")
    q = StorageQueue(db, _AlwaysOnline())
    expires = time.time() + 600
    for c in candidates:
        q._queue.append((bytes(c), each_bytes, expires))
    return db, q


def test_fulfill_spreads_over_min_peers_when_queue_is_deep(loop):
    requester = b"\xa0" * 32
    candidates = [bytes([0xB0 + i]) * 32 for i in range(6)]
    db, q = _queue_with_candidates(candidates, 10_000)
    loop.run_until_complete(q.fulfill(requester, 600, min_peers=6))
    negotiated = db.get_client_negotiated_peers(requester)
    assert sorted(negotiated) == sorted(candidates)  # all six, 100 each
    for c in candidates:
        assert db.get_clients_storing_on(c) == [requester]


def test_fulfill_stays_greedy_with_a_shallow_queue(loop):
    # 2-3-client deployments must see exactly the pre-erasure behavior:
    # the spread cap only arms when the queue could plausibly reach
    # min_peers distinct candidates
    requester = b"\xa1" * 32
    candidates = [b"\xc1" * 32, b"\xc2" * 32]
    db, q = _queue_with_candidates(candidates, 10_000)
    loop.run_until_complete(q.fulfill(requester, 600, min_peers=6))
    assert db.get_client_negotiated_peers(requester) == [candidates[0]]


# --------------------------------------------------------------------------
# chaos end-to-end: the striped acceptance scenario
# --------------------------------------------------------------------------


def _corpus(root, rng):
    root.mkdir(parents=True, exist_ok=True)
    (root / "docs").mkdir()
    (root / "big.bin").write_bytes(rng.randbytes(300_000))
    (root / "docs" / "notes.txt").write_bytes(rng.randbytes(90_000))
    (root / "small.cfg").write_bytes(b"alpha=1\nbeta=2\n")


def _tree_digest(root):
    out = {}
    for p in sorted(root.rglob("*")):
        if p.is_file():
            out[str(p.relative_to(root))] = hashlib.sha256(
                p.read_bytes()).hexdigest()
    return out


def test_chaos_stripe_sourceless_repair_and_two_dark_restore(
        tmp_path, loop, monkeypatch, plane):
    from backuwup_tpu.app import ClientApp
    from backuwup_tpu.net.server import CoordinationServer

    monkeypatch.setattr(defaults, "PACKFILE_TARGET_SIZE", 64 * 1024)
    monkeypatch.setattr(defaults, "ACK_TIMEOUT_S", 1.5)
    monkeypatch.setattr(defaults, "RESTORE_REQUEST_THROTTLE_S", 0.0)
    monkeypatch.setattr(defaults, "AUDIT_SERVE_MIN_INTERVAL_S", 0.0)
    rng = random.Random(21)
    _corpus(tmp_path / "a_src", rng)
    source_digest = _tree_digest(tmp_path / "a_src")
    k, m = defaults.RS_K, defaults.RS_M
    n = k + m
    assert (k, m) == (4, 2)  # the scenario below is written for 4+2

    async def run():
        server = CoordinationServer(db_path=str(tmp_path / "server.db"))
        port = await server.start()

        def make_app(name):
            app = ClientApp(config_dir=tmp_path / name / "cfg",
                            data_dir=tmp_path / name / "data",
                            server_addr=f"127.0.0.1:{port}",
                            backend=CpuBackend(CDCParams.from_desired(4096)))
            app.store.set_backup_path(str(tmp_path / "a_src"))
            return app

        a = make_app("a")
        holders = [make_app(f"p{i}") for i in range(1, n + 1)]
        spare = make_app("spare")
        apps = [a] + holders + [spare]
        for app in apps:
            await app.start()
            app._audit_task.cancel()  # deterministic: tests drive audits
        a.engine.auto_repair = False

        # manual negotiation (matchmaking has its own tests).  The six
        # holders get the larger allowance so free-space ordering places
        # every stripe on them; the spare sorts last and stays fresh for
        # the sourceless rebuild to re-home onto.
        for peer, amt in [(p, 8 << 20) for p in holders] + \
                         [(spare, 6 << 20)]:
            a.store.add_peer_negotiated(peer.client_id, amt)
            peer.store.add_peer_negotiated(a.client_id, amt)
            server.db.save_storage_negotiated(
                bytes(a.client_id), bytes(peer.client_id), amt)

        # --- backup: every packfile becomes a k+m stripe ------------------
        snapshot = await asyncio.wait_for(a.backup(), 180)
        assert snapshot
        pids = set()
        for p in holders:
            rows = a.store.shard_placements_for_peer(p.client_id)
            assert rows, "every holder must carry part of the backup"
            for pid, _size, idx in rows:
                assert idx >= 0, "nothing may fall back to whole placement"
                pids.add(bytes(pid))
        assert len(pids) >= 2, "corpus must span several packfiles"
        for pid in pids:
            srows = a.store.shards_for_packfile(pid)
            assert sorted(i for _, i in srows) == list(range(n))
            assert len({bytes(peer) for peer, _ in srows}) == n
        assert a.store.shard_placements_for_peer(spare.client_id) == []
        # acked stripes delete the local packfiles (fan-out dirs remain)
        assert not [p for p in a.engine._pack_dir().rglob("*")
                    if p.is_file()]

        # --- the local source tree is GONE: repair must be sourceless ----
        shutil.rmtree(tmp_path / "a_src")

        # --- first holder dies and is audit-demoted ----------------------
        p1 = holders[0]
        lost_rows = a.store.shard_placements_for_peer(p1.client_id)
        assert len(lost_rows) == len(pids)  # one shard of every stripe
        plane.kill(p1.client_id)
        await p1.stop()
        t0 = time.time()
        for i in range(defaults.AUDIT_DEMOTE_MISSES):
            res = await a.engine.audit_peer(p1.client_id, now=t0 + i)
            assert res is not None and not res.passed
        assert a.store.get_audit_state(p1.client_id).demoted

        # --- one repair round rebuilds the lost shards from survivors ----
        report = await asyncio.wait_for(
            a.engine.repair_round(now=t0 + 10), 180)
        assert report["shards_rebuilt"] == len(pids)
        assert report["packfiles"] == 0  # nothing orphaned, no re-pack
        assert report["bytes_replaced"] > 0
        assert bytes(p1.client_id).hex() in report["peers"]
        assert a.store.placements_for_peer(p1.client_id) == []
        spare_rows = a.store.shard_placements_for_peer(spare.client_id)
        assert len(spare_rows) == len(pids)
        for pid in pids:  # full n-coverage again, p1 replaced by spare
            srows = a.store.shards_for_packfile(pid)
            assert sorted(i for _, i in srows) == list(range(n))
            assert bytes(p1.client_id) not in {bytes(p) for p, _ in srows}
        n_reports = server.db._db.execute(
            "SELECT COUNT(*) FROM repair_reports WHERE peer = ?",
            (bytes(p1.client_id),)).fetchone()[0]
        assert n_reports == 1

        # --- a second holder goes dark: restore on any 4 of 6 ------------
        p2 = holders[1]
        plane.kill(p2.client_id)
        await p2.stop()
        dest = tmp_path / "restored"
        await asyncio.wait_for(a.restore(dest), 180)
        assert _tree_digest(dest) == source_digest  # byte-for-byte

        for app in apps:
            if app not in (p1, p2):
                await app.stop()
        await server.stop()

    loop.run_until_complete(asyncio.wait_for(run(), 500))
