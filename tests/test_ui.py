"""L5 UI layer: dashboard server, WS push/commands, CLI first-run flow,
restore-from-phrase, and the executable entry points."""

import asyncio
import io
import json
import random

import aiohttp
import pytest

from backuwup_tpu.app import ClientApp
from backuwup_tpu.crypto import KeyManager, phrase_to_secret, secret_to_phrase
from backuwup_tpu.net.server import CoordinationServer
from backuwup_tpu.ops.backend import CpuBackend
from backuwup_tpu.ops.gear import CDCParams
from backuwup_tpu.ui import cli as ui_cli
from backuwup_tpu.ui.server import UIServer

SMALL = CDCParams.from_desired(4096)


@pytest.fixture
def loop():
    loop = asyncio.new_event_loop()
    yield loop
    loop.close()


# --- CLI first-run flow (ui/cli.rs) ----------------------------------------


def test_recovery_phrase_roundtrip_via_cli(capsys=None):
    keys = KeyManager.generate()
    out = io.StringIO()
    ui_cli.print_recovery_phrase(keys.root_secret, out=out)
    text = out.getvalue()
    assert "RECOVERY PHRASE" in text
    phrase = secret_to_phrase(keys.root_secret)
    assert phrase in text
    assert phrase_to_secret(phrase) == keys.root_secret


def test_first_run_guide_fresh_and_restore():
    out = io.StringIO()
    answers = iter(["n"])
    assert ui_cli.first_run_guide(lambda _: next(answers), out) is None

    keys = KeyManager.generate()
    phrase = secret_to_phrase(keys.root_secret)
    answers = iter(["x", "r", "not a phrase", phrase])
    secret = ui_cli.first_run_guide(lambda _: next(answers), out)
    assert secret == keys.root_secret
    assert "not valid" in out.getvalue()


# --- restore-from-phrase (identity.rs:46-69) --------------------------------


def test_client_app_from_phrase_rebuilds_identity(tmp_path):
    a = ClientApp(config_dir=tmp_path / "a", data_dir=tmp_path / "a_data",
                  server_addr="127.0.0.1:1", backend=CpuBackend(SMALL))
    phrase = secret_to_phrase(a.keys.root_secret)
    b = ClientApp.from_phrase(
        phrase, config_dir=tmp_path / "b", data_dir=tmp_path / "b_data",
        server_addr="127.0.0.1:1", backend=CpuBackend(SMALL))
    assert b.client_id == a.client_id
    assert b.fresh_identity  # store was empty; secret persisted
    c = ClientApp(config_dir=tmp_path / "b", data_dir=tmp_path / "b_data",
                  server_addr="127.0.0.1:1", backend=CpuBackend(SMALL))
    assert c.client_id == a.client_id and not c.fresh_identity


def test_client_app_refuses_conflicting_identity(tmp_path):
    ClientApp(config_dir=tmp_path / "a", data_dir=tmp_path / "a_data",
              server_addr="127.0.0.1:1", backend=CpuBackend(SMALL))
    other = KeyManager.generate()
    with pytest.raises(ValueError, match="different identity"):
        ClientApp(config_dir=tmp_path / "a", data_dir=tmp_path / "a_data",
                  server_addr="127.0.0.1:1", backend=CpuBackend(SMALL),
                  root_secret=other.root_secret)


# --- dashboard server -------------------------------------------------------


def test_ui_server_serves_spa_and_dispatches_commands(tmp_path, loop):
    """GET / returns the dashboard; the WS channel round-trips config
    commands and pushes progress/log events (ws_dispatcher.rs:16-66)."""

    async def run():
        app = ClientApp(config_dir=tmp_path / "cfg",
                        data_dir=tmp_path / "data",
                        server_addr="127.0.0.1:1",
                        backend=CpuBackend(SMALL))
        ui = UIServer(app, bind="127.0.0.1:0")
        url = await ui.start()
        async with aiohttp.ClientSession() as session:
            async with session.get(url) as resp:
                assert resp.status == 200
                body = await resp.text()
                assert "backuwup" in body and "/ws" in body

            async with session.ws_connect(url + "/ws") as ws:
                # initial tick arrives for late joiners
                first = json.loads((await ws.receive_str()))
                assert first["kind"] == "progress"

                await ws.send_str(json.dumps({
                    "command": "config",
                    "backup_path": str(tmp_path / "src")}))
                kinds = {}
                for _ in range(2):
                    ev = json.loads(await ws.receive_str())
                    kinds[ev["kind"]] = ev
                assert "config" in kinds
                assert kinds["config"]["payload"]["backup_path"] == \
                    str(tmp_path / "src")
                assert app.store.get_backup_path() == str(tmp_path / "src")

                await ws.send_str(json.dumps({"command": "get_config"}))
                ev = json.loads(await ws.receive_str())
                assert ev["kind"] == "config"

                await ws.send_str(json.dumps({"command": "nope"}))
                ev = json.loads(await ws.receive_str())
                assert ev["kind"] == "error"
        await ui.stop()
        await app.stop()

    loop.run_until_complete(asyncio.wait_for(run(), 30))


def test_ui_ticker_pushes_progress_and_peers(tmp_path, loop):
    """While a backup runs, connected clients get ticker progress frames and
    peer telemetry at the configured cadences (backup/mod.rs:109-114,
    ws_status_message.rs:128-163)."""

    async def run():
        app = ClientApp(config_dir=tmp_path / "cfg",
                        data_dir=tmp_path / "data",
                        server_addr="127.0.0.1:1",
                        backend=CpuBackend(SMALL))
        app.store.add_peer_negotiated(b"\x05" * 32, 12345)
        ui = UIServer(app, bind="127.0.0.1:0")
        url = await ui.start()
        app.messenger.progress_state.running = True  # simulate active backup
        kinds = set()
        async with aiohttp.ClientSession() as session:
            async with session.ws_connect(url + "/ws") as ws:
                async def drain():
                    while {"progress", "peers"} - kinds:
                        ev = json.loads(await ws.receive_str())
                        kinds.add(ev["kind"])
                        if ev["kind"] == "peers" and ev["payload"]["peers"]:
                            peer = ev["payload"]["peers"][0]
                            assert peer["negotiated"] == 12345
                await asyncio.wait_for(drain(), 10)
        assert {"progress", "peers"} <= kinds
        await ui.stop()
        await app.stop()

    loop.run_until_complete(asyncio.wait_for(run(), 30))


def test_backup_driven_from_ws_command(tmp_path, loop):
    """The full VERDICT ask: drive a real two-client backup through the
    dashboard's start_backup command and watch it finish over /ws."""
    rng = random.Random(9)
    src_a = tmp_path / "a_src"
    src_b = tmp_path / "b_src"
    for d, tag in ((src_a, "a"), (src_b, "b")):
        d.mkdir()
        (d / "f.bin").write_bytes(rng.randbytes(150_000))
        (d / "t.txt").write_bytes(f"hi {tag}".encode())

    async def run():
        server = CoordinationServer(db_path=str(tmp_path / "server.db"))
        port = await server.start()
        addr = f"127.0.0.1:{port}"

        def make_app(name, src):
            app = ClientApp(config_dir=tmp_path / name / "cfg",
                            data_dir=tmp_path / name / "data",
                            server_addr=addr, backend=CpuBackend(SMALL))
            app.store.set_backup_path(str(src))
            return app

        a = make_app("a", src_a)
        b = make_app("b", src_b)
        await a.start()
        await b.start()
        ui = UIServer(a, bind="127.0.0.1:0")
        url = await ui.start()

        # B backs up concurrently so A's storage request has a counterparty
        b_task = asyncio.create_task(b.backup())
        async with aiohttp.ClientSession() as session:
            async with session.ws_connect(url + "/ws") as ws:
                await ws.send_str(json.dumps({"command": "start_backup"}))

                async def wait_finish():
                    while True:
                        ev = json.loads(await ws.receive_str())
                        if ev["kind"] == "backup_finished":
                            return ev["payload"]["snapshot"]
                        assert ev["kind"] != "error", ev
                snap_hex = await asyncio.wait_for(wait_finish(), 60)
        assert len(bytes.fromhex(snap_hex)) == 32
        await asyncio.wait_for(b_task, 60)
        assert server.db.get_latest_client_snapshot(a.client_id) == \
            bytes.fromhex(snap_hex)

        await ui.stop()
        await a.stop()
        await b.stop()
        await server.stop()

    loop.run_until_complete(asyncio.wait_for(run(), 120))
