"""SustainedWindow contract: stated scale AND minimum wall clock."""

import time

import bench_configs


def test_items_honors_n_min_with_sustain_disabled(monkeypatch):
    monkeypatch.setenv("BENCH_MIN_WALL_S", "0")
    w = bench_configs.SustainedWindow(5)
    got = list(w.items(["a", "b"]))
    assert got == ["a", "b", "a", "b", "a"]
    assert w.count == 5


def test_passes_honors_n_min_with_sustain_disabled(monkeypatch):
    monkeypatch.setenv("BENCH_MIN_WALL_S", "0")
    w = bench_configs.SustainedWindow(3)
    assert list(w.passes()) == [0, 1, 2]
    assert w.count == 3


def test_window_extends_to_min_wall(monkeypatch):
    monkeypatch.setenv("BENCH_MIN_WALL_S", "0.2")
    w = bench_configs.SustainedWindow(1)
    n = 0
    for _ in w.passes():
        n += 1
        time.sleep(0.05)
    # the contract is "extends past n_min until min wall", not an exact
    # pass count (sleep overshoot on a loaded box would make that flaky)
    assert n >= 2
    assert w.wall >= 0.2
