"""Composed chaos scenarios and the scorecard gate (scenario/).

Tier 1 runs the seeded ``composed`` scenario — sustained churn,
byzantine corrupt-shard peers, sourceless repair, and backup + restore +
repair racing the engine's exclusivity lock — and requires the scorecard
to pass with zero invariant-violation-seconds.  A second fast test
proves the acceptance flip: an injected UNREPAIRED peer loss must move
``bkw_durability_stripes_degraded`` and the server ``/healthz`` to
degraded within one monitor sweep.  The rest of the matrix is slow.
"""

import asyncio

import pytest

from backuwup_tpu.obs import journal as obs_journal
from backuwup_tpu.obs import metrics as obs_metrics
from backuwup_tpu.scenario import (Phase, ScenarioHarness,
                                   builtin_scenarios, run_scenario)

pytestmark = pytest.mark.scenario


@pytest.fixture(autouse=True)
def _isolate():
    """Zero the process registry and drop any installed journal so one
    scenario's durability gauges never leak into the next test's
    healthz."""
    obs_metrics.registry().reset()
    yield
    obs_metrics.registry().reset()
    obs_journal.uninstall()


@pytest.fixture
def loop():
    loop = asyncio.new_event_loop()
    yield loop
    loop.close()


def test_composed_scenario_passes_the_scorecard_gate(tmp_path, loop):
    card = loop.run_until_complete(
        run_scenario(builtin_scenarios()["composed"], tmp_path))
    assert card.passed, card.render()
    # steady state held: no second with a durability invariant violated
    assert card.invariants["violation_seconds"] == 0
    # the byzantine demotion really forced sourceless shard rebuilds
    assert card.counters.get("bkw_repair_shards_rebuilt_total", 0) >= 1
    # and the race phase really raced: the exclusivity lock turned
    # concurrent attempts away before they eventually ran
    assert any(k.startswith("bkw_engine_busy_rejections_total")
               for k in card.counters), card.counters
    assert card.invariants["final"]["status"] == "ok"


def test_unrepaired_loss_flips_gauge_and_healthz_in_one_sweep(
        tmp_path, loop):
    import aiohttp

    spec = builtin_scenarios()["loss"]

    async def run():
        h = ScenarioHarness(spec, tmp_path)
        await h.setup()
        try:
            await h._phase_backup(Phase("backup"))
            assert h.monitor.sweep().status == "ok"
            await h._phase_kill(Phase("kill"))  # dark + demoted, NO repair
            rep = h.monitor.sweep()  # the one sweep the flip is due in
            assert rep.status == "degraded"
            assert rep.stripes_degraded > 0
            assert rep.repair_debt_bytes > 0
            snap = obs_metrics.registry().snapshot()
            fam = snap["bkw_durability_stripes_degraded"]
            assert sum(s["value"] for s in fam["series"]) > 0
            async with aiohttp.ClientSession() as http:
                url = f"http://127.0.0.1:{h.server_port}/healthz"
                async with http.get(url) as resp:
                    doc = await resp.json()
            # degraded is a warning, not an outage: 200 with the facts
            assert doc["status"] == "degraded"
            assert doc["durability"]["stripes_degraded"] > 0
        finally:
            await h.teardown()

    loop.run_until_complete(run())


def test_wan_scenario_resumes_and_places_by_capacity(tmp_path, loop):
    """The wan scenario severs chunked shard sends mid-transfer with
    armed exact-offset cuts; the scorecard gates prove transfers resumed
    from the receiver's verified partial (not restart-from-zero) and
    that placement obeyed the seeded capacity measurements."""
    card = loop.run_until_complete(
        run_scenario(builtin_scenarios()["wan"], tmp_path))
    assert card.passed, card.render()
    resumes = sum(v for k, v in card.counters.items()
                  if k.startswith("bkw_transfer_resumes_total"))
    assert resumes >= 1, card.counters
    # injected cuts really fired (fault plane accounting), and the
    # re-sent byte budget stayed a small fraction of payload moved
    assert any(k.startswith("bkw_fault_injections_total")
               for k in card.counters), card.counters
    resent = sum(v for k, v in card.counters.items()
                 if k.startswith("bkw_transfer_bytes_resent_total"))
    sent = sum(v for k, v in card.counters.items()
               if k.startswith("bkw_transfer_bytes_total"))
    assert resent <= 0.25 * max(sent, 1.0)
    gates = {a.name: a.passed for a in card.assertions}
    assert gates.get("placement_capacity_aware") is True
    assert gates.get("placement_demotion_recovered") is True


@pytest.mark.slow
@pytest.mark.parametrize("name",
                         ["steady", "churn", "byzantine", "loss", "full"])
def test_scenario_matrix(name, tmp_path, loop):
    card = loop.run_until_complete(
        run_scenario(builtin_scenarios()[name], tmp_path))
    assert card.passed, card.render()
