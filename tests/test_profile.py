"""Pipeline profiler (obs/profile.py): dispatch accounting + reports.

The dispatch counts must be EXACT on the CPU-fallback path (tier-1 pins
``JAX_PLATFORMS=cpu``): every expected number below is an independent
hand count derived from the stage semantics documented in the
obs/profile.py module table and the file layout alone — one scan+select
per stream, one gather per stream that produced chunks, one batched
digest per ``manifest_many`` call, one index classification per pack
batch.  The e2e test runs a full backup through the scenario harness
and checks the whole acceptance bundle: non-zero per-stage counts
matching the hand count, a ``pipeline_report`` journal event, a
Perfetto-loadable timeline merging sender and receiver spans under one
trace id, and per-peer estimator rows that survive a client restart.
"""

import asyncio
import importlib.util
import json
from pathlib import Path

import pytest

from backuwup_tpu.crypto import KeyManager
from backuwup_tpu.obs import journal as obs_journal
from backuwup_tpu.obs import metrics as obs_metrics
from backuwup_tpu.obs import profile
from backuwup_tpu.ops.backend import CpuBackend
from backuwup_tpu.ops.gear import CDCParams
from backuwup_tpu.scenario import Phase, ScenarioSpec, run_scenario
from backuwup_tpu.snapshot.blob_index import BlobIndex
from backuwup_tpu.snapshot.packer import DirPacker
from backuwup_tpu.snapshot.packfile import PackfileWriter
from backuwup_tpu.store import Store

KEYS = KeyManager.from_secret(bytes(range(32)))
SMALL = CDCParams.from_desired(4096)


def test_unknown_stage_rejected():
    with pytest.raises(ValueError):
        profile.dispatch("upload")


def test_dispatch_counts_manifest_many_exact(rng):
    """Hand count for one batched CPU manifest_many call: 3 streams
    (one empty) -> scan=3 select=3 gather=2 digest=1 index=0."""
    base = profile.baseline()
    streams = [rng.randbytes(20_000), rng.randbytes(5_000), b""]
    manifests = CpuBackend(SMALL).manifest_many(streams)
    rep = profile.report(base)
    assert rep["dispatches"] == {
        # one chunk() pass per stream, empty or not
        "scan": 3, "select": 3,
        # the empty stream produced no chunks, so no slicing pass
        "gather": 2,
        # ONE batched digest_many per manifest_many call
        "digest": 1,
        # no pack batch involved
        "index": 0,
    }
    total = sum(len(s) for s in streams)
    assert rep["bytes"]["scan"] == total
    assert rep["bytes"]["select"] == total
    # CDC chunks tile each stream exactly, so gather/digest bytes are
    # the non-empty payload
    assert rep["bytes"]["gather"] == total
    assert rep["bytes"]["digest"] == total
    # the CPU fallback never pads
    assert rep["pad_efficiency"]["scan"] == 1.0
    assert rep["pad_efficiency"]["digest"] == 1.0
    assert rep["pad_efficiency"]["index"] is None
    # sanity: the manifests really cover the streams
    assert [sum(r.length for r in m) for m in manifests] == \
        [len(s) for s in streams]


def test_dispatch_counts_packer_hand_count(tmp_path, rng):
    """Hand count for a DirPacker tree: the packer batches per
    directory (one flush per dir with files, everything far below
    batch_bytes), so with d0=3 files, d1=2 files, root=1 file:
    scan=select=gather=6, digest=3 (one per batch), index=3."""
    src = tmp_path / "src"
    (src / "d0").mkdir(parents=True)
    (src / "d1").mkdir()
    (src / "d2").mkdir()  # empty dir: no batch, no dispatches
    layout = {"d0/a.bin": 9_000, "d0/b.bin": 7_000, "d0/c.bin": 5_000,
              "d1/d.bin": 8_000, "d1/e.bin": 6_000, "top.bin": 10_000}
    for rel, size in layout.items():
        (src / rel).write_bytes(rng.randbytes(size))

    index = BlobIndex(KEYS, tmp_path / "index")
    writer = PackfileWriter(
        KEYS, tmp_path / "pack",
        on_packfile=lambda pid, path, hashes, size:
            index.finalize_packfile(pid, hashes))
    packer = DirPacker(CpuBackend(SMALL), writer, index)

    base = profile.baseline()
    snapshot = packer.pack(src)
    rep = profile.report(base)

    assert len(snapshot) == 32
    assert packer.stats.files == 6
    assert rep["dispatches"] == {
        "scan": 6, "select": 6, "gather": 6, "digest": 3, "index": 3}
    total = sum(layout.values())
    assert rep["bytes"]["scan"] == total
    assert rep["bytes"]["digest"] == total
    # index bytes are 32 per classified chunk ref; every chunk the
    # manifests produced was classified exactly once
    assert rep["bytes"]["index"] == 32 * packer.stats.chunks
    assert rep["pad_efficiency"]["index"] == 1.0


def test_report_is_a_delta_and_journals(tmp_path):
    jr = obs_journal.install(obs_journal.Journal(tmp_path / "j.jsonl"))
    try:
        profile.dispatch("digest", actual_bytes=100, padded_bytes=400)
        base = profile.baseline()
        profile.dispatch("digest", count=2, actual_bytes=512,
                         padded_bytes=1024)
        rep = profile.report(base)
        # the pre-baseline dispatch is invisible in the delta
        assert rep["dispatches"]["digest"] == 2
        assert rep["bytes"]["digest"] == 512
        assert rep["padded_bytes"]["digest"] == 1024
        assert rep["pad_efficiency"]["digest"] == 0.5
        assert rep["dispatches"]["scan"] == 0
        profile.emit_report(rep, snapshot="ab" * 32, backend="cpu")
    finally:
        obs_journal.uninstall()
    lines = [json.loads(l) for l in
             (tmp_path / "j.jsonl").read_text().splitlines()]
    events = [l for l in lines if l["kind"] == "pipeline_report"]
    assert len(events) == 1
    assert events[0]["report"]["dispatches"]["digest"] == 2
    assert events[0]["backend"] == "cpu"
    # the cumulative gauge tracks all-time bytes, not the delta
    eff = obs_metrics.registry().get("bkw_pipeline_pad_efficiency")
    reg = obs_metrics.registry()
    all_actual = reg.get("bkw_pipeline_stage_bytes_total")
    all_padded = reg.get("bkw_pipeline_stage_padded_bytes_total")
    assert eff.value(stage="digest") == pytest.approx(
        all_actual.value(stage="digest") / all_padded.value(stage="digest"))


def test_devtime_shim_reexports_the_library_api():
    """scripts/devtime.py must stay a thin wrapper over obs/profile.py
    (the runbook's ``from scripts.devtime import dev_time`` contract)."""
    path = Path(__file__).resolve().parent.parent / "scripts" / "devtime.py"
    spec = importlib.util.spec_from_file_location("devtime_shim", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    assert mod.dev_time is profile.dev_time
    assert mod.dev_time_stage is profile.dev_time_stage
    assert mod._sync is profile._sync


@pytest.mark.profile
def test_dev_time_stage_records_histogram_and_journal(tmp_path):
    """Timing-sensitive: excluded from tier-1 via the profile marker
    (BKW_PROFILE_TESTS=1 to run)."""
    import jax
    import jax.numpy as jnp

    fn = jax.jit(lambda x: x * 2 + 1)
    x = jnp.ones(128, jnp.float32)
    jr = obs_journal.install(obs_journal.Journal(tmp_path / "j.jsonl"))
    try:
        dt = profile.dev_time_stage("scan", fn, x, n=5)
    finally:
        obs_journal.uninstall()
    assert dt > 0
    hist = obs_metrics.registry().get("bkw_profile_stage_seconds")
    assert hist.sum_value(stage="scan") >= dt * 0.99
    lines = [json.loads(l) for l in
             (tmp_path / "j.jsonl").read_text().splitlines()]
    assert any(l["kind"] == "profile" and l["stage"] == "scan"
               for l in lines)


# --- the e2e acceptance bundle ----------------------------------------------

@pytest.fixture
def isolated(tmp_path):
    """The test_scenario _isolate idiom: zero the process registry and
    drop any journal so this run's gauges never leak across tests."""
    obs_metrics.registry().reset()
    yield
    obs_metrics.registry().reset()
    obs_journal.uninstall()


@pytest.mark.scenario
def test_backup_e2e_perf_plane_acceptance(tmp_path, isolated):
    """One CPU-fallback backup through the loopback deployment must
    produce: non-zero per-stage dispatch counts matching an independent
    hand count, a pipeline_report journal event, a Perfetto-loadable
    timeline merging sender and receiver spans under one trace id, and
    persisted per-peer estimator rows that survive a client restart."""
    from backuwup_tpu.obs import timeline as obs_timeline

    spec = ScenarioSpec(name="perf_e2e", seed=7,
                        phases=(Phase("backup"),))
    jpath = tmp_path / "journal.jsonl"
    obs_journal.install(obs_journal.Journal(jpath))
    base = profile.baseline()
    loop = asyncio.new_event_loop()
    try:
        card = loop.run_until_complete(
            run_scenario(spec, tmp_path / "run"))
    finally:
        loop.close()
        obs_journal.uninstall()
    assert card.passed, card.render()
    # the scorecard's own telemetry gate fired on real deltas
    assert any(a.name == "telemetry_flowing" and a.passed
               for a in card.assertions)

    # 1) dispatch counts: the harness corpus is 6 small files split
    # d0/d1, so the packer hand count is scan=select=gather=6,
    # digest=2 (one per directory batch), index=2
    rep = profile.report(base)
    assert rep["dispatches"] == {
        "scan": 6, "select": 6, "gather": 6, "digest": 2, "index": 2}
    assert all(rep["bytes"][s] > 0 for s in profile.STAGES)

    # 2) the backup journaled its pipeline report, matching the deltas
    lines = [json.loads(l) for l in jpath.read_text().splitlines()]
    reports = [l for l in lines if l["kind"] == "pipeline_report"]
    assert len(reports) == 1
    assert reports[0]["report"]["dispatches"] == rep["dispatches"]
    assert reports[0]["snapshot"]  # tied to the snapshot it profiled

    # 3) Perfetto timeline: sender transfer spans and receiver store
    # spans merge under the one backup trace id
    doc = obs_timeline.export_timeline(
        [jpath], tmp_path / "timeline.json", labels=["perf_e2e"])
    events = doc["traceEvents"]
    assert doc["displayTimeUnit"] == "ms"
    spans = [e for e in events if e["ph"] == "X"]
    sends = [e for e in spans if e["name"] == "transfer.send"]
    stores = [e for e in spans if e["name"] == "receiver.store"]
    assert sends and stores
    tids = {e["args"]["trace_id"] for e in sends}
    assert len(tids) == 1  # one backup, one trace
    assert tids == {e["args"]["trace_id"] for e in stores}
    # and the written file is valid JSON with the same events
    loaded = json.loads((tmp_path / "timeline.json").read_text())
    assert len(loaded["traceEvents"]) == len(events)

    # 4) per-peer estimators persisted: reopen the sender's config DB
    # (the "client restart") and the rows are still there
    store = Store(directory=tmp_path / "run" / "a" / "cfg",
                  data_base=tmp_path / "run" / "a" / "data")
    try:
        rows = store.all_peer_stats()
        assert rows, "no persisted peer estimator rows"
        assert all(r.samples > 0 and r.throughput_bps > 0 for r in rows)
    finally:
        store.close()
