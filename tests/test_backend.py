"""Backend-level parity: the production TPU manifest path vs the CPU oracle.

The device-resident batch path (`DevicePipeline.manifest_batch` behind
`TpuBackend.manifest_many`) must produce bit-identical chunk boundaries and
digests to `CpuBackend` — dedup ratios depend on it (SURVEY.md section 7
hard part 1).
"""

import random

import pytest

from backuwup_tpu.ops.backend import CpuBackend, TpuBackend, select_backend
from backuwup_tpu.ops.gear import CDCParams

PARAMS = CDCParams.from_desired(4096)


def _assert_manifests_equal(a, b):
    assert len(a) == len(b)
    for ma, mb in zip(a, b):
        assert [(r.offset, r.length, r.hash) for r in ma] == \
            [(r.offset, r.length, r.hash) for r in mb]


@pytest.fixture(scope="module")
def backends():
    return CpuBackend(PARAMS), TpuBackend(PARAMS)


def test_manifest_many_parity_mixed_sizes(backends, rng=random.Random(5)):
    cpu, tpu = backends
    streams = [
        b"",                       # empty file
        b"x",                      # single byte
        rng.randbytes(100),        # < min_size (single runt chunk)
        rng.randbytes(PARAMS.min_size),          # exactly min
        rng.randbytes(5000),
        rng.randbytes(65536),      # exactly one segment bucket
        rng.randbytes(65537),      # just over a bucket boundary
        rng.randbytes(200_000),    # multi-chunk
        b"\x00" * 50_000,          # no candidates -> max-size forced cuts
        rng.randbytes(60_000) * 2,  # internal duplication
    ]
    _assert_manifests_equal(cpu.manifest_many(streams),
                            tpu.manifest_many(streams))


def test_manifest_many_parity_large_batch(backends, rng=random.Random(6)):
    """Many small files of one bucket — the vmapped batch dispatch."""
    cpu, tpu = backends
    streams = [rng.randbytes(rng.randrange(1, 30_000)) for _ in range(64)]
    _assert_manifests_equal(cpu.manifest_many(streams),
                            tpu.manifest_many(streams))


def test_manifest_stream_matches_manifest(backends, rng=random.Random(7)):
    cpu, tpu = backends
    data = rng.randbytes(300_000)
    pos = [0]

    def read(n):
        out = data[pos[0]:pos[0] + n]
        pos[0] += n
        return out

    refs = tpu.manifest_stream(read, segment_bytes=64 * 1024)
    assert [(r.offset, r.length, r.hash) for r in refs] == \
        [(r.offset, r.length, r.hash) for r in cpu.manifest(data)]


def test_select_backend_policy():
    assert select_backend("cpu").name == "cpu"
    assert select_backend("tpu").name == "tpu"


def test_native_backend_matches_cpu():
    pytest.importorskip("ctypes")
    from backuwup_tpu.ops.backend import NativeBackend
    from backuwup_tpu.native import NativeUnavailable
    try:
        nat = NativeBackend(PARAMS)
    except NativeUnavailable:
        pytest.skip("native toolchain unavailable")
    cpu = CpuBackend(PARAMS)
    data = random.Random(11).randbytes(200_000)
    got = nat.manifest_many([data, b"", data[:100]])
    want = cpu.manifest_many([data, b"", data[:100]])
    assert [[(r.offset, r.length, r.hash) for r in refs] for refs in got] \
        == [[(r.offset, r.length, r.hash) for r in refs] for refs in want]
