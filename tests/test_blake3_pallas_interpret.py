"""Pallas BLAKE3 leaf-kernel logic vs the XLA path and the spec oracle.

The Mosaic lowering is proven on hardware by ``pallas_digest_available``'s
runtime parity gate; here the kernel BODY runs in the pallas interpreter
on CPU, pinning the masking/flag/counter logic and the (g, 256, R, 128)
word tiling against both the XLA leaf loop and the scalar spec
implementation.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from conftest import pallas_interpret_works
from backuwup_tpu.ops.blake3_cpu import blake3_hash
from backuwup_tpu.ops.blake3_tpu import _root_cv_to_digests, digest_padded

if not pallas_interpret_works():  # pragma: no cover
    pytest.skip("pallas interpret mode unavailable on this host",
                allow_module_level=True)


@pytest.mark.parametrize("B,L", [(8, 8), (16, 4)])
def test_leaf_kernel_matches_xla_and_spec(B, L):
    rng = np.random.default_rng(77)
    buf = rng.integers(0, 256, (B, L * 1024), dtype=np.uint8)
    # every masking regime: empty, sub-block, block-boundary straddles,
    # chunk boundaries, full
    lens = np.resize(np.array([0, 1, 63, 64, 65, 1023, 1024, 1025,
                               2048, 4000, L * 1024 - 1, L * 1024],
                              dtype=np.int32), B)
    a = np.asarray(digest_padded(jnp.asarray(buf), jnp.asarray(lens),
                                 L=L, pallas=False))
    b = np.asarray(digest_padded(jnp.asarray(buf), jnp.asarray(lens),
                                 L=L, pallas=True, pallas_interpret=True))
    assert (a == b).all(), "pallas leaf kernel diverged from XLA path"
    digests = _root_cv_to_digests(b)  # the production conversion path
    for r in range(B):
        want = blake3_hash(bytes(buf[r, :lens[r]]))
        assert digests[r] == want, f"row {r} len {lens[r]} spec divergence"
