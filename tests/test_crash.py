"""Crash-consistency plane: injection, per-seam recovery, the matrix.

Deterministic crash injection (utils/faults.py ``crashpoint`` sites) and
the startup recovery sweep (``Engine.recover``) are the two halves of
docs/crash_consistency.md; this module pins both ends of the contract:

* the crash plane itself — site registry completeness, inert-by-default,
  exact arming, ``BaseException`` semantics, ``BKW_FAULTS`` parsing;
* the durable-commit helpers and the config DB's WAL pragmas;
* per-seam unit recoveries: debris planted exactly as a crash at each
  commit point leaves it, then ``recover()`` — which must reconcile on
  the first run and reconcile ZERO items on the second (idempotence);
* the composed crash-matrix scenario (representative seams tier-1, the
  full sender-side matrix slow);
* a subprocess kill-9 e2e: a real client process hard-exits at an armed
  seam mid-backup (``crash_hard`` → ``os._exit(70)``), restarts, sweeps,
  re-backs-up, and restores byte-identical data.
"""

import asyncio
import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time
from pathlib import Path

import pytest

from backuwup_tpu import defaults, wire
from backuwup_tpu.crypto import KeyManager
from backuwup_tpu.engine import Engine
from backuwup_tpu.net import serverstore as _serverstore  # noqa: F401
from backuwup_tpu.net.p2p import PartialStore, ReceivedFilesWriter
from backuwup_tpu.obs import journal as obs_journal
from backuwup_tpu.obs import metrics as obs_metrics
from backuwup_tpu.ops.blake3_cpu import blake3_hash
from backuwup_tpu.scenario import builtin_scenarios, run_scenario
from backuwup_tpu.snapshot.blob_index import (BlobIndex, ChallengeEntry,
                                              ChallengeTable,
                                              index_file_name)
from backuwup_tpu.snapshot.packfile import (PackfileReader, PackfileWriter,
                                            packfile_path)
from backuwup_tpu.store import Store
from backuwup_tpu.utils import durable, faults
from backuwup_tpu.wire import Blob, BlobKind

pytestmark = pytest.mark.crash

KEYS = KeyManager.from_secret(bytes(range(32)))

#: Every commit seam the plane must know about (importing engine / p2p /
#: snapshot above registers them all; a seam added without registration
#: would escape the crash matrix, which is exactly what this test is for).
EXPECTED_SITES = {
    "challenge.save.pre", "challenge.save.post",
    "index.save.pre", "index.save.post",
    "pack.seal.pre", "pack.seal.post",
    "partial.sink.pre", "partial.sink.post",
    "placement.insert.pre", "placement.insert.post",
    "repair.rehome.pre", "repair.rehome.post",
    "stripe.finish.pre", "stripe.finish.post",
    # the GC state machine's seams (docs/lifecycle.md)
    "gc.prune.pre", "gc.prune.post",
    "gc.sweep.pre", "gc.sweep.post",
    "gc.compact.seal.pre", "gc.compact.seal.post",
    "gc.swap.pre", "gc.swap.post",
    "gc.reclaim.pre", "gc.reclaim.post",
    # the cold dedup tier's run commits (docs/dedup_tiering.md)
    "tier.run.commit.pre", "tier.run.commit.post",
    "tier.compact.commit.pre", "tier.compact.commit.post",
    # the replicated op log's commit points (docs/server.md §Replication)
    "repl.log.append.pre", "repl.log.append.post",
    "repl.ship.acked",
    "repl.promote.pre", "repl.promote.post",
}


@pytest.fixture(autouse=True)
def _isolate():
    obs_metrics.registry().reset()
    yield
    obs_metrics.registry().reset()
    obs_journal.uninstall()
    faults.uninstall()


@pytest.fixture
def loop():
    loop = asyncio.new_event_loop()
    yield loop
    loop.close()


@pytest.fixture
def plane():
    return faults.install(faults.FaultPlane(seed=7))


def _blob(data: bytes, kind=BlobKind.FILE_CHUNK) -> Blob:
    return Blob(hash=blake3_hash(data), kind=kind, data=data)


def _mk_engine(tmp_path):
    store = Store(tmp_path / "cfg", data_base=tmp_path / "data")
    engine = Engine(KEYS, store, None, None)
    # recover() would otherwise spawn a background repair round for any
    # drain backlog; these unit tests drive every sweep themselves
    engine.auto_repair = False
    return engine, store


def _write_packfile(out_dir):
    """One real sealed packfile on disk; returns (pid, path, hashes)."""
    written = []
    w = PackfileWriter(KEYS, out_dir,
                       on_packfile=lambda pid, path, hashes, size:
                       written.append((pid, path, hashes)))
    w.add_blob(_blob(b"crash test payload " * 64))
    w.flush()
    w.close()
    return written[0]


# --- the injection plane ---------------------------------------------------


def test_crash_site_registry_enumerates_every_commit_seam():
    sites = faults.crash_sites()
    assert EXPECTED_SITES <= set(sites)
    assert list(sites) == sorted(sites)  # stable matrix input


def test_crashpoint_is_inert_without_a_plane_or_arming():
    faults.uninstall()
    faults.crashpoint("pack.seal.pre")  # no plane: pure no-op
    plane = faults.install(faults.FaultPlane(seed=1))
    faults.crashpoint("pack.seal.pre")  # plane but nothing armed
    assert plane.fired == {}


def test_armed_crashpoint_fires_once_with_site_and_accounting(plane):
    plane.arm_crash("pack.seal.pre")
    with pytest.raises(faults.CrashInjected) as e:
        faults.crashpoint("pack.seal.pre")
    assert e.value.site == "pack.seal.pre"
    assert plane.fired["crash.pack.seal.pre"] == 1
    # one-shot: the armed index is consumed, later passes are clean
    faults.crashpoint("pack.seal.pre")
    assert plane.fired["crash.pack.seal.pre"] == 1
    snap = obs_metrics.registry().snapshot()
    series = snap["bkw_fault_injections_total"]["series"]
    assert any(s["labels"].get("site") == "crash.pack.seal.pre"
               and s["value"] == 1 for s in series)


def test_crash_injected_outruns_blanket_exception_guards(plane):
    assert not issubclass(faults.CrashInjected, Exception)
    plane.arm_crash("index.save.pre")
    with pytest.raises(faults.CrashInjected):
        try:
            faults.crashpoint("index.save.pre")
        except Exception:  # the guard a real power cut never runs
            pytest.fail("except Exception swallowed the injected crash")


def test_from_env_parses_crash_specs():
    plane = faults.from_env(
        "seed=3,crash=placement.insert.post@1+pack.seal.pre,crash_hard=1")
    assert plane.crash_hard
    assert plane._armed["crash.placement.insert.post"] == {1}
    assert plane._armed["crash.pack.seal.pre"] == {0}
    rated = faults.from_env("crash_rate=0.5")
    assert rated.crash == 0.5 and not rated.crash_hard
    assert faults.from_env("") is None
    with pytest.raises(ValueError):
        faults.from_env("crash_everything=1")


# --- durable-commit helpers + DB pragmas -----------------------------------


def test_write_replace_commits_atomically_without_tmp_debris(tmp_path):
    dst = tmp_path / "state.bin"
    durable.write_replace(dst, b"v1")
    assert dst.read_bytes() == b"v1"
    durable.write_replace(dst, b"v2")
    assert dst.read_bytes() == b"v2"
    assert list(tmp_path.glob("*.tmp")) == []


def test_commit_replace_moves_tmp_over_destination(tmp_path):
    tmp, dst = tmp_path / "x.tmp", tmp_path / "x"
    dst.write_bytes(b"old")
    tmp.write_bytes(b"new")
    durable.commit_replace(tmp, dst)
    assert dst.read_bytes() == b"new"
    assert not tmp.exists()


def test_config_db_runs_wal_with_normal_sync(tmp_path):
    store = Store(tmp_path / "cfg", data_base=tmp_path / "data")
    try:
        mode, = store._db.execute("PRAGMA journal_mode").fetchone()
        assert mode.lower() == "wal"
        sync, = store._db.execute("PRAGMA synchronous").fetchone()
        assert int(sync) == 1  # NORMAL
    finally:
        store.close()


# --- per-seam commit windows -----------------------------------------------


def test_challenge_save_crash_windows(plane, tmp_path):
    ct = ChallengeTable(KEYS, tmp_path)
    entries = [ChallengeEntry(0, 16, b"\x01" * wire.AUDIT_NONCE_LEN,
                              b"\x02" * 32)]
    pid = bytes(wire.PACKFILE_ID_LEN)
    plane.arm_crash("challenge.save.pre")
    with pytest.raises(faults.CrashInjected):
        ct.save(pid, entries)
    # pre-commit crash: nothing published, only the tmp the sweep deletes
    assert not ct.has(pid)
    tmp = ct.path(pid).with_suffix(".tmp")
    assert tmp.is_file()
    tmp.unlink()  # what recover()'s tmp sweep does
    ct.save(pid, entries)  # the retry after recovery commits cleanly
    got = ct.load(pid)
    assert [(e.offset, e.length) for e in got] == [(0, 16)]

    pid2 = b"\x01" * wire.PACKFILE_ID_LEN
    plane.arm_crash("challenge.save.post")
    with pytest.raises(faults.CrashInjected):
        ct.save(pid2, entries)
    # post-commit crash: the table IS durable, nothing to redo
    assert ct.has(pid2)
    assert len(ct.load(pid2)) == 1


def test_blob_index_crash_burns_the_tmp_counter_nonce(plane, tmp_path):
    idx_dir = tmp_path / "index"
    idx = BlobIndex(KEYS, idx_dir)
    idx.finalize_packfile(b"\x01" * wire.PACKFILE_ID_LEN, [b"\xaa" * 32])
    plane.arm_crash("index.save.pre")
    with pytest.raises(faults.CrashInjected):
        idx.flush()
    # the tmp for counter 0 is on disk; the commit never happened
    assert (idx_dir / (index_file_name(0) + ".tmp")).is_file()
    assert not (idx_dir / index_file_name(0)).is_file()
    # a restarted index must NOT reuse counter 0: the counter is the
    # AES-GCM nonce, and the crashed tmp may already hold ciphertext
    idx2 = BlobIndex(KEYS, idx_dir)
    assert idx2.load() == 0
    idx2.finalize_packfile(b"\x02" * wire.PACKFILE_ID_LEN, [b"\xbb" * 32])
    written = idx2.flush()
    assert [p.name for p in written] == [index_file_name(1)]
    idx3 = BlobIndex(KEYS, idx_dir)
    assert idx3.load() == 1


def test_pack_seal_crash_leaves_only_tmp_debris(plane, tmp_path):
    w = PackfileWriter(KEYS, tmp_path / "pack")
    w.add_blob(_blob(b"doomed bytes"))
    plane.arm_crash("pack.seal.pre")
    with pytest.raises(faults.CrashInjected):
        w.flush()
    files = [p for p in (tmp_path / "pack").rglob("*") if p.is_file()]
    assert files and all(p.suffix == ".tmp" for p in files)
    w.shutdown()


# --- Engine.recover(): per-seam unit recoveries ----------------------------


def test_recover_cleans_planted_debris_and_is_idempotent(tmp_path, loop):
    engine, store = _mk_engine(tmp_path)
    try:
        # crashed tmp+replace commits in all three commit directories
        for d, name in ((store.index_dir(), "000004.tmp"),
                        (store.challenge_dir(), "ab12.tmp"),
                        (engine._pack_dir() / "ab", "cd34.tmp")):
            d.mkdir(parents=True, exist_ok=True)
            (d / name).write_bytes(b"torn")
        # half-staged repair and restore trees
        for staging in (store.data_base / "repair_staging",
                        store.restore_dir()):
            staging.mkdir(parents=True, exist_ok=True)
            (staging / "half.bin").write_bytes(b"x")
        # an abandoned inbound partial, older than the TTL
        part = store.received_dir(b"\x11" * 32) / "partial"
        part.mkdir(parents=True, exist_ok=True)
        old = time.time() - defaults.PARTIAL_STORE_TTL_S - 60
        for name in ("ff00.bin", "ff00.json"):
            (part / name).write_bytes(b"{}")
            os.utime(part / name, (old, old))

        rep = loop.run_until_complete(engine.recover())
        assert rep["tmp_cleaned"] == 3
        assert rep["staging_cleared"] == 2
        assert rep["partials_expired"] == 1
        assert rep["reconciled"] == 6
        assert engine.last_recovery is rep

        again = loop.run_until_complete(engine.recover())
        assert again["reconciled"] == 0

        snap = obs_metrics.registry().snapshot()
        runs = snap["bkw_recovery_runs_total"]["series"]
        assert sum(s["value"] for s in runs) == 2
        cats = {s["labels"]["category"]: s["value"]
                for s in snap["bkw_recovery_items_total"]["series"]}
        assert cats["tmp_cleaned"] == 3 and cats["partials_expired"] == 1
    finally:
        store.close()


def test_recover_adopts_verified_packfiles_the_index_never_named(
        tmp_path, loop):
    engine, store = _mk_engine(tmp_path)
    try:
        # a crash after pack.seal.post but before the index flush: the
        # sealed file exists, the on-disk index has never heard of it
        pid, _path, hashes = _write_packfile(engine._pack_dir())
        rep = loop.run_until_complete(engine.recover())
        assert rep["packfiles_adopted"] == 1
        assert rep["packfiles_pending"] == 1  # still unsent: drain backlog
        assert engine.index.lookup(hashes[0]) == bytes(pid)
        # the adoption was flushed: a fresh index sees it too
        fresh = BlobIndex(KEYS, store.index_dir())
        assert fresh.load() >= 1
        assert bytes(pid) in fresh.packfile_ids()

        again = loop.run_until_complete(engine.recover())
        assert again["packfiles_adopted"] == 0
        assert again["reconciled"] == 0
    finally:
        store.close()


def test_recover_drops_torn_packfiles(tmp_path, loop):
    engine, store = _mk_engine(tmp_path)
    try:
        pid = b"\x5a" * wire.PACKFILE_ID_LEN
        path = packfile_path(engine._pack_dir(), pid)
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_bytes(b"\x00" * 64)  # a torn seal: header never decrypts
        rep = loop.run_until_complete(engine.recover())
        assert rep["packfiles_corrupt"] == 1
        assert not path.exists()
        assert loop.run_until_complete(engine.recover())["reconciled"] == 0
    finally:
        store.close()


def test_recover_retires_unreachable_placements(tmp_path, loop):
    engine, store = _mk_engine(tmp_path)
    try:
        # a placement row whose packfile neither the index nor the local
        # disk can name: the blob mapping died with the crashed process
        store.record_placement(b"\x6b" * wire.PACKFILE_ID_LEN,
                               b"\x22" * 32, 4096, shard_index=0)
        rep = loop.run_until_complete(engine.recover())
        assert rep["placements_retired"] == 1
        assert store.all_placements() == []
        assert loop.run_until_complete(engine.recover())["reconciled"] == 0
    finally:
        store.close()


def test_recover_completes_fully_placed_packfiles(tmp_path, loop):
    engine, store = _mk_engine(tmp_path)
    try:
        # crash between the last placement ack and the local unlink: every
        # byte is on a peer, only the local cleanup was lost
        pid, path, hashes = _write_packfile(engine._pack_dir())
        engine.index.finalize_packfile(pid, hashes)
        engine.index.flush()
        store.record_placement(pid, b"\x33" * 32, path.stat().st_size,
                               shard_index=-1)
        rep = loop.run_until_complete(engine.recover())
        assert rep["packfiles_completed"] == 1
        assert not path.exists()
        assert len(store.all_placements()) == 1  # the ack stays recorded
        assert loop.run_until_complete(engine.recover())["reconciled"] == 0
    finally:
        store.close()


def test_partial_sink_crash_debris_and_ttl_janitor(plane, tmp_path, loop):
    store = Store(tmp_path / "cfg", data_base=tmp_path / "data")
    try:
        store.set_obfuscation_key(b"\x01\x02\x03\x04")
        peer = b"\x42" * 32
        store.add_peer_negotiated(peer, 1 << 20)
        writer = ReceivedFilesWriter(store, peer)
        data = b"w" * 1024
        part0 = dict(file_info=wire.FileInfoKind.PACKFILE,
                     file_id=b"\x05" * wire.PACKFILE_ID_LEN,
                     data=data[:512], offset=0, total=len(data),
                     digest=blake3_hash(data))

        plane.arm_crash("partial.sink.pre")
        with pytest.raises(faults.CrashInjected):
            loop.run_until_complete(writer.sink_part(**part0))
        # pre-append crash: nothing staged, the sender restarts from 0
        assert not list(writer.partials.base.glob("*.bin"))

        plane.arm_crash("partial.sink.post")
        with pytest.raises(faults.CrashInjected):
            loop.run_until_complete(writer.sink_part(**part0))
        # post-append crash: the staged prefix survives for resume...
        assert len(list(writer.partials.base.glob("*.bin"))) == 1
        # ...but an abandoned one is the TTL janitor's to reclaim
        old = time.time() - defaults.PARTIAL_STORE_TTL_S - 60
        for p in writer.partials.base.iterdir():
            os.utime(p, (old, old))
        assert writer.partials.expire() == 1
        assert not list(writer.partials.base.iterdir())
        assert writer.partials.expire() == 0
        snap = obs_metrics.registry().snapshot()
        expired = snap["bkw_partials_expired_total"]["series"]
        assert sum(s["value"] for s in expired) == 1
    finally:
        store.close()


# --- the crash-matrix scenario ---------------------------------------------


@pytest.mark.scenario
def test_crash_scenario_recovers_representative_seams(tmp_path, loop):
    """Three representative commit seams (pack seal, index save, placement
    insert) crash mid-backup; each must recover idempotently with zero
    invariant violations and the final restore must be byte-for-byte."""
    card = loop.run_until_complete(
        run_scenario(builtin_scenarios()["crash"], tmp_path))
    assert card.passed, card.render()
    gates = {a.name: a.passed for a in card.assertions}
    assert gates["crashes_injected"] and gates["recovery_clean"]
    runs = sum(v for k, v in card.counters.items()
               if k.startswith("bkw_recovery_runs_total"))
    assert runs >= 6  # one sweep per restart + one idempotence probe each
    assert card.invariants["violation_seconds"] == 0
    assert card.invariants["final"]["status"] == "ok"


@pytest.mark.scenario
@pytest.mark.slow
def test_crash_scenario_full_sender_matrix(tmp_path, loop):
    card = loop.run_until_complete(
        run_scenario(builtin_scenarios()["crash_full"], tmp_path))
    assert card.passed, card.render()


# --- subprocess kill-9 e2e -------------------------------------------------

REPO = Path(__file__).resolve().parent.parent


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _spawn(args, extra_env=None):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    env.pop("BKW_FAULTS", None)
    if extra_env:
        env.update(extra_env)
    return subprocess.Popen(
        [sys.executable, "-m", "backuwup_tpu", *args], cwd=REPO, env=env,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        bufsize=1)


def _reader(proc):
    import queue
    if getattr(proc, "_line_queue", None) is None:
        q = queue.Queue()

        def pump():
            for line in proc.stdout:
                q.put(line)
            q.put(None)

        threading.Thread(target=pump, daemon=True).start()
        proc._line_queue = q
    return proc._line_queue


def _wait_line(proc, needle: str, timeout: float = 120) -> str:
    import queue
    deadline = time.monotonic() + timeout
    q = _reader(proc)
    lines = []
    while True:
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            break
        try:
            line = q.get(timeout=remaining)
        except queue.Empty:
            break
        if line is None:
            raise AssertionError(
                f"process exited before {needle!r}:\n{''.join(lines)}")
        lines.append(line)
        if needle in line:
            return line
    raise AssertionError(f"timeout waiting for {needle!r}:\n{''.join(lines)}")


def _stop(proc):
    if proc is not None and proc.poll() is None:
        proc.send_signal(signal.SIGINT)
        try:
            proc.wait(15)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait(15)


def _ws_url(dash_line: str) -> str:
    return dash_line.rsplit("at ", 1)[1].strip().rstrip("/") + "/ws"


async def _start_backups_until_crash(ws_a: str, ws_b: str):
    """Kick off both backups, then drain A's events until the injected
    hard crash severs the socket — proof the process died mid-backup."""
    import aiohttp

    async with aiohttp.ClientSession() as session:
        async with session.ws_connect(ws_a) as wa, \
                session.ws_connect(ws_b) as wb:
            await wa.send_str(json.dumps({"command": "start_backup"}))
            await wb.send_str(json.dumps({"command": "start_backup"}))
            while True:
                msg = await wa.receive()
                if msg.type != aiohttp.WSMsgType.TEXT:
                    return


async def _backup_then_restore(ws_a: str, src_a: Path):
    import aiohttp

    async with aiohttp.ClientSession() as session:
        async with session.ws_connect(ws_a) as wa:
            await wa.send_str(json.dumps({"command": "start_backup"}))
            while True:
                ev = json.loads(await wa.receive_str())
                assert ev["kind"] != "error", ev
                if ev["kind"] == "backup_finished":
                    break
            for p in sorted(src_a.rglob("*"), reverse=True):
                p.unlink() if p.is_file() else p.rmdir()
            await wa.send_str(json.dumps({"command": "start_restore"}))
            while True:
                ev = json.loads(await wa.receive_str())
                assert ev["kind"] != "error", ev
                if ev["kind"] == "restore_finished":
                    return


@pytest.mark.slow
def test_kill9_mid_backup_then_recovery_restores_bytes(tmp_path):
    """A real client process hard-exits (``os._exit``) at the
    placement.insert.post seam mid-backup, restarts over the same
    directories, sweeps, finishes the backup, and restores its corpus
    byte-for-byte — the whole crash story through the user entry point."""
    import random

    rng = random.Random(11)
    src_a, src_b = tmp_path / "a_src", tmp_path / "b_src"
    files_a = {}
    for d, tag in ((src_a, "a"), (src_b, "b")):
        (d / "sub").mkdir(parents=True)
        data = {"f.bin": rng.randbytes(300_000),
                "sub/nested.txt": f"hello {tag}\n".encode()}
        for rel, blob in data.items():
            (d / rel).write_bytes(blob)
        if tag == "a":
            files_a = data

    def client_args(name, src):
        return ["client", "--non-interactive",
                "--server-addr", f"127.0.0.1:{port}",
                "--config-dir", str(tmp_path / name / "cfg"),
                "--data-dir", str(tmp_path / name / "data"),
                "--backup-path", str(src),
                "--ui-bind", "127.0.0.1:0"]

    port = _free_port()
    server = _spawn(["server", "--bind", f"127.0.0.1:{port}",
                     "--db", str(tmp_path / "srv.db")])
    a = b = None
    try:
        _wait_line(server, f"listening on 127.0.0.1:{port}")
        b = _spawn(client_args("b", src_b))
        ws_b = _ws_url(_wait_line(b, "dashboard at"))
        # the doomed client: first placement commit hard-exits (code 70)
        a = _spawn(client_args("a", src_a),
                   extra_env={"BKW_FAULTS":
                              "crash=placement.insert.post,crash_hard=1"})
        ws_a = _ws_url(_wait_line(a, "dashboard at"))

        asyncio.run(asyncio.wait_for(
            _start_backups_until_crash(ws_a, ws_b), 120))
        assert a.wait(60) == faults.CRASH_EXIT_CODE

        # restart over the same directories, fault-free
        a = _spawn(client_args("a", src_a))
        _wait_line(a, "recovery:")  # the startup sweep announced itself
        ws_a = _ws_url(_wait_line(a, "dashboard at"))
        asyncio.run(asyncio.wait_for(
            _backup_then_restore(ws_a, src_a), 240))

        for rel, blob in files_a.items():
            assert (src_a / rel).read_bytes() == blob, rel
    finally:
        _stop(a)
        _stop(b)
        _stop(server)
