"""Federated coordination plane (net/ring.py, PartitionedServerStore,
cross-node work stealing, client failover — the PR-15 federation).

Tier 1 covers:

* consistent-hash ring semantics — ownership stability under node
  add (bounded key movement) and remove (only the removed node's keys
  move), steal-order parity with the matchmaker's home-shard-last walk;
* PartitionedServerStore routing — first-pubkey routing, fan-out reads
  merged across partitions, reclaim on both endpoint partitions;
* the matchmaker's remote-steal leg — consulted only after every local
  shard is empty, and ``serve_steal``'s candidate-side invariants
  (record-first, rollback on failed push);
* client failover — a refused dial rotates to the next configured node
  without double-submitting, a received response is always final, and
  a wrong-node 421 redirect is followed exactly once;
* the 3-node kill/revive churn swarm (builtin ``federation`` spec) with
  its zero-lost-matchmakings scorecard gate.

The multi-process scaling legs (scenario/federation.py) and the soak
swarm are slow — bench config 16 is their gate.
"""

import asyncio
import dataclasses
import socket

import pytest

from backuwup_tpu import defaults
from backuwup_tpu.crypto import KeyManager
from backuwup_tpu.net import client as net_client
from backuwup_tpu.net.matchmaking import ShardedMatchmaker
from backuwup_tpu.net.ring import (HashRing, partition_key, partition_of,
                                   successors)
from backuwup_tpu.net.server import CoordinationServer
from backuwup_tpu.net.serverstore import (PartitionedServerStore,
                                          SqliteServerStore)
from backuwup_tpu.obs import metrics as obs_metrics
from backuwup_tpu.scenario import builtin_swarms, run_swarm
from backuwup_tpu.store import Store

pytestmark = pytest.mark.federation

MIB = 1 << 20


@pytest.fixture(autouse=True)
def _isolate():
    obs_metrics.registry().reset()
    yield
    obs_metrics.registry().reset()


@pytest.fixture
def loop():
    loop = asyncio.new_event_loop()
    yield loop
    loop.close()


def pk(i: int) -> bytes:
    return i.to_bytes(8, "big") + bytes(24)


# --- ring semantics ---------------------------------------------------------


def test_ring_ownership_stable_under_add():
    """Adding a node to an N-node ring moves ~1/(N+1) of the keys and
    ONLY toward the new node — every moved key must land on it."""
    nodes = [f"node{i}" for i in range(4)]
    keys = [pk(i) for i in range(4000)]
    ring = HashRing(nodes)
    before = {k: ring.owner(k) for k in keys}
    ring.add("node4")
    moved = {k for k in keys if ring.owner(k) != before[k]}
    assert all(ring.owner(k) == "node4" for k in moved)
    # expected fraction 1/5; 64 vnodes keeps the variance modest
    assert len(moved) / len(keys) < 0.40


def test_ring_remove_moves_only_its_own_keys():
    nodes = [f"node{i}" for i in range(4)]
    keys = [pk(i) for i in range(4000)]
    ring = HashRing(nodes)
    before = {k: ring.owner(k) for k in keys}
    ring.remove("node2")
    for k in keys:
        if before[k] == "node2":
            assert ring.owner(k) != "node2"
        else:
            # a survivor's keys never move on a remove
            assert ring.owner(k) == before[k]


def test_ring_successors_disjoint_and_stable_at_every_size():
    """Replication-chain property sweep over N = 1..64: for every
    partition the successor chain never contains the owner, has no
    duplicates, and is exactly min(count, N-1) long; and removing a
    node OUTSIDE owner+chain leaves both owner and chain untouched
    (the promote-on-death blast radius is the chain, nothing else)."""
    parts = range(8)
    for n in range(1, 65):
        ring = HashRing([f"node{i}" for i in range(n)])
        for part in parts:
            owner = ring.owner(partition_key(part))
            chain = successors(ring, part, count=3)
            assert owner not in chain
            assert len(chain) == len(set(chain)) == min(3, n - 1)
            involved = {owner, *chain}
            outsider = next((f"node{i}" for i in range(n)
                             if f"node{i}" not in involved), None)
            if outsider is None:
                continue  # every node is on this partition's chain
            ring.remove(outsider)
            assert ring.owner(partition_key(part)) == owner
            assert successors(ring, part, count=3) == chain
            ring.add(outsider)  # hash-positioned: exact inverse


def test_ring_steal_order_home_last_parity():
    """steal_order(n) is the OTHER nodes in ring-successor order —
    the federated continuation of the matchmaker's home-shard-last
    walk: self is excluded (home served locally), and walking from
    each node's order must traverse the same cyclic sequence."""
    ring = HashRing([f"node{i}" for i in range(5)])
    order = ring.nodes()
    assert sorted(order) == sorted(f"node{i}" for i in range(5))
    for nid in order:
        steal = ring.steal_order(nid)
        assert nid not in steal
        assert len(steal) == len(order) - 1
        at = order.index(nid)
        assert steal == order[at + 1:] + order[:at]


def test_ring_empty_and_partition_of():
    assert HashRing([]).owner(pk(1)) is None
    assert HashRing([]).steal_order("nodeX") == []
    parts = defaults.SERVER_STORE_PARTITIONS
    for i in range(100):
        p = partition_of(pk(i), parts)
        assert 0 <= p < parts
        assert p == partition_of(pk(i), parts)  # stable


# --- partitioned store routing ----------------------------------------------


def test_partitioned_store_routes_and_fans_out(tmp_path, loop):
    store = PartitionedServerStore(str(tmp_path / "parts"), partitions=4)
    try:
        # place two sources in different partitions
        a = next(pk(i) for i in range(100)
                 if store.partition_for(pk(i)) is store.parts[0])
        b = next(pk(i) for i in range(100)
                 if store.partition_for(pk(i)) is store.parts[1])
        dest = pk(9999)
        for key in (a, b, dest):
            store.register_client(key)
            assert store.client_exists(key)
        store.save_storage_negotiated(a, dest, MIB)
        store.save_storage_negotiated(dest, a, MIB)
        store.save_storage_negotiated(b, dest, MIB)
        store.save_storage_negotiated(dest, b, MIB)
        # fan-out read sees rows living in different partitions
        storing_on = store.get_clients_storing_on(dest)
        assert set(storing_on) == {a, b}
        # audit fan-out: distinct failing reporters summed across the
        # partitions their reports route to (by-reporter placement)
        store.save_audit_report(a, dest, False, "t")
        store.save_audit_report(b, dest, False, "t")
        assert store.audit_failing_reporters(dest, 3600) == 2
        # reclaim touches both endpoint partitions
        assert store.reclaim_negotiation(a, dest) >= 1
        assert dest not in set(store.get_clients_storing_on(a))
    finally:
        store.close()


def test_partitioned_store_write_behind_durable(tmp_path, loop):
    store = PartitionedServerStore(str(tmp_path / "parts"), partitions=2)
    try:
        async def run():
            await store.aio.register_client(pk(1))
            await store.aio.save_storage_negotiated(pk(1), pk(2), MIB)

        loop.run_until_complete(run())
        store.flush()
        assert store.client_exists(pk(1))
        # the reverse edge: pk(1) is the source storing on pk(2)
        assert store.get_clients_storing_on(pk(2)) == [pk(1)]
    finally:
        store.close()


# --- remote steal -----------------------------------------------------------


class StubConns:
    def __init__(self):
        self.fail_notify = set()
        self.notified = {}

    def is_online(self, client_id) -> bool:
        return True

    async def notify(self, client_id, msg) -> bool:
        await asyncio.sleep(0)
        if bytes(client_id) in self.fail_notify:
            return False
        self.notified.setdefault(bytes(client_id), []).append(msg)
        return True


def test_remote_steal_only_after_local_shards_empty(tmp_path, loop):
    """A local candidate must be matched locally; the remote leg fires
    only when every local shard came up empty."""
    store = SqliteServerStore(str(tmp_path / "s.db"))
    conns = StubConns()
    queue = ShardedMatchmaker(store, conns, expiry_s=30)
    calls = []

    async def remote(requester, want, share_cap):
        calls.append(int(want))
        return None

    queue.remote_steal = remote
    try:
        async def run():
            await queue.fulfill(pk(1), MIB)       # enqueues pk(1)
            assert calls == [MIB]                  # ring was starved
            calls.clear()
            await queue.fulfill(pk(2), MIB)       # matches pk(1) locally
            assert calls == []                     # remote leg not taken
            assert pk(1) in conns.notified and pk(2) in conns.notified

        loop.run_until_complete(run())
    finally:
        store.close()


def test_remote_steal_hit_notifies_requester(tmp_path, loop):
    store = SqliteServerStore(str(tmp_path / "s.db"))
    conns = StubConns()
    queue = ShardedMatchmaker(store, conns, expiry_s=30)

    async def remote(requester, want, share_cap):
        return pk(77), int(want)

    queue.remote_steal = remote
    try:
        async def run():
            await queue.fulfill(pk(1), MIB)
            [msg] = conns.notified[pk(1)]
            assert msg.destination_id == pk(77)
            assert msg.storage_available == MIB

        loop.run_until_complete(run())
    finally:
        store.close()


def test_serve_steal_records_both_edges_and_pushes(tmp_path, loop):
    store = SqliteServerStore(str(tmp_path / "s.db"))
    conns = StubConns()
    queue = ShardedMatchmaker(store, conns, expiry_s=30)
    remote_requester = pk(500)
    try:
        async def run():
            await queue.fulfill(pk(1), MIB)        # queue a local candidate
            served = await queue.serve_steal(remote_requester, MIB)
            assert served == (pk(1), MIB)
            # the local candidate got its push; the requester push is
            # the REQUESTER node's job
            assert pk(1) in conns.notified
            assert remote_requester not in conns.notified

        loop.run_until_complete(run())
        store.flush()
        assert set(store.get_clients_storing_on(remote_requester)) == {pk(1)}
        assert set(store.get_clients_storing_on(pk(1))) == {remote_requester}
    finally:
        store.close()


def test_serve_steal_rolls_back_on_failed_candidate_push(tmp_path, loop):
    store = SqliteServerStore(str(tmp_path / "s.db"))
    conns = StubConns()
    conns.fail_notify.add(pk(1))
    queue = ShardedMatchmaker(store, conns, expiry_s=30)
    try:
        async def run():
            await queue.fulfill(pk(1), MIB)
            assert await queue.serve_steal(pk(500), MIB) is None

        loop.run_until_complete(run())
        store.flush()
        assert store.get_clients_storing_on(pk(500)) == []
    finally:
        store.close()


# --- client failover --------------------------------------------------------


def _keys(tag: int) -> KeyManager:
    return KeyManager.from_secret(tag.to_bytes(4, "big").ljust(32, b"\x55"))


def test_client_failover_on_refused_dial_no_double_submit(tmp_path, loop):
    async def run():
        server = CoordinationServer(db_path=str(tmp_path / "s.db"))
        port = await server.start()
        # a port nothing listens on: the dial is REFUSED, which is the
        # only condition that may rotate (the request never reached any
        # server, so a retry cannot double-submit)
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            dead = s.getsockname()[1]
        c = net_client.ServerClient(
            _keys(1), Store(tmp_path / "c1"),
            addr=[f"127.0.0.1:{dead}", f"127.0.0.1:{port}"], tls=False)
        try:
            await c.register()
            assert c.failovers == 1
            assert await server.db.aio.client_exists(
                bytes(_keys(1).client_id))
        finally:
            await c.close()
            await server.stop()

    loop.run_until_complete(run())


def test_client_received_response_is_final(tmp_path, loop):
    """A typed server response must NOT rotate.  The identity is
    registered ONLY on the second configured server; a login dialed at
    the first gets a typed CLIENT_NOT_FOUND — if the client treated
    that as a failover trigger, the retry against the second server
    would wrongly succeed."""
    async def run():
        s1 = CoordinationServer(db_path=str(tmp_path / "s1.db"))
        s2 = CoordinationServer(db_path=str(tmp_path / "s2.db"))
        p1, p2 = await s1.start(), await s2.start()
        seed = net_client.ServerClient(
            _keys(2), Store(tmp_path / "seed"),
            addr=f"127.0.0.1:{p2}", tls=False)
        c = net_client.ServerClient(
            _keys(2), Store(tmp_path / "c2"),
            addr=[f"127.0.0.1:{p1}", f"127.0.0.1:{p2}"], tls=False)
        try:
            await seed.register()
            assert await s2.db.aio.client_exists(bytes(_keys(2).client_id))
            with pytest.raises(net_client.ClientNotFound):
                await c.login()
            assert c.failovers == 0
        finally:
            await seed.close()
            await c.close()
            await s1.stop()
            await s2.stop()

    loop.run_until_complete(run())


def test_wrong_node_redirect_followed_once(tmp_path, loop):
    """A session-less request landing on the wrong federation node gets
    a 421 + NodeRedirect toward the ring owner; the client follows it
    (once, and only to a configured URL) so a stale node list never
    loses the matchmaking."""
    async def run():
        s0 = CoordinationServer(db_path=str(tmp_path / "s0.db"))
        s1 = CoordinationServer(db_path=str(tmp_path / "s1.db"))
        p0, p1 = await s0.start(), await s1.start()
        ring = HashRing(["node0", "node1"])
        peers = {"node0": f"http://127.0.0.1:{p0}",
                 "node1": f"http://127.0.0.1:{p1}"}
        s0.enable_federation("node0", ring, peers)
        s1.enable_federation("node1", ring, peers)
        # a key the ring homes on node1, dialed at node0 first
        tag = next(t for t in range(3, 200)
                   if ring.owner(bytes(_keys(t).client_id)) == "node1")
        c = net_client.ServerClient(
            _keys(tag), Store(tmp_path / "c3"),
            addr=[f"127.0.0.1:{p0}", f"127.0.0.1:{p1}"], tls=False)
        try:
            await c.register()
            # the redirect steered the registration to the owner
            assert await s1.db.aio.client_exists(
                bytes(_keys(tag).client_id))
            assert not await s0.db.aio.client_exists(
                bytes(_keys(tag).client_id))
        finally:
            await c.close()
            await s0.stop()
            await s1.stop()

    loop.run_until_complete(run())


# --- the churn swarm --------------------------------------------------------


@pytest.mark.timeout(240)
def test_federation_swarm_kill_revive(tmp_path, loop):
    """Tier-1 federation acceptance: 3 nodes over one partitioned
    store, a node killed and revived on its port mid-run.  The
    scorecard's hard gates: zero lost matchmakings (durable rows >= 2x
    matchmakings across every partition), at least one client failover,
    matchmaking flow after the revive, bounded p99."""
    spec = builtin_swarms()["federation"]
    card, summary = loop.run_until_complete(run_swarm(spec, tmp_path))
    assert card.passed, card.render()
    gates = {a.name: a.passed for a in card.assertions}
    for gate in ("federation_no_lost_matchmakings",
                 "federation_failover_exercised",
                 "federation_post_revive_flow",
                 "federation_p99_bounded",
                 "commits_off_event_loop"):
        assert gates.get(gate) is True, (gate, card.render())
    assert summary["nodes"] == 3
    assert summary["node_kills"] == 1
    assert summary["negotiated_rows"] >= 2 * summary["total_matchmakings"]


@pytest.mark.slow
@pytest.mark.timeout(600)
def test_federation_swarm_soak(tmp_path, loop):
    spec = builtin_swarms()["federation_soak"]
    card, summary = loop.run_until_complete(run_swarm(spec, tmp_path))
    assert card.passed, card.render()
    assert summary["negotiated_rows"] >= 2 * summary["total_matchmakings"]
    assert summary["failovers"] >= 1


@pytest.mark.slow
@pytest.mark.timeout(600)
def test_federation_multiprocess_legs(tmp_path):
    """The bench's scaling legs end-to-end: real OS processes, real
    /fed/steal HTTP.  Throughput gates are bench config 16's (armed on
    >=4-CPU hosts); here every node must produce matches and the fleet
    must complete cleanly."""
    from backuwup_tpu.scenario.federation import (FederationLoadSpec,
                                                  run_federation_load)
    out = run_federation_load(
        FederationLoadSpec(nodes=2, clients=32, duration_s=1.0), tmp_path)
    assert out["matchmakings"] > 0
    assert len(out["per_node"]) == 2
    for node in out["per_node"]:
        assert node["fulfills"] > 0
