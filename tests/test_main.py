"""Entry-point smoke tests: the system must run as OS processes
(``python -m backuwup_tpu client|server``; client/src/main.rs:44-85,
server/src/main.rs:40-65)."""

import os
import signal
import subprocess
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent


def _spawn(args, env_extra=None):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.setdefault("USE_TLS", "0")
    env.update(env_extra or {})
    return subprocess.Popen(
        [sys.executable, "-m", "backuwup_tpu", *args],
        cwd=REPO, env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        text=True)


def _wait_line(proc, needle, timeout=60):
    deadline = time.time() + timeout
    lines = []
    while time.time() < deadline:
        line = proc.stdout.readline()
        if not line:
            if proc.poll() is not None:
                break
            continue
        lines.append(line)
        if needle in line:
            return line
    raise AssertionError(
        f"never saw {needle!r}; got {lines!r}, stderr={proc.stderr.read()!r}")


def _stop(proc):
    if proc.poll() is None:
        proc.send_signal(signal.SIGTERM)
        try:
            proc.wait(10)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait(10)


def test_server_and_client_processes(tmp_path):
    """Launch the coordination server and a client as real processes; the
    client registers, prints its recovery phrase, and serves its
    dashboard."""
    server = _spawn(["server", "--bind", "127.0.0.1:18100",
                     "--db", str(tmp_path / "srv.db")])
    try:
        _wait_line(server, "listening on 127.0.0.1:18100")
        client = _spawn(
            ["client", "--non-interactive",
             "--config-dir", str(tmp_path / "cfg"),
             "--data-dir", str(tmp_path / "data"),
             "--server-addr", "127.0.0.1:18100",
             "--ui-bind", "127.0.0.1:0"])
        try:
            _wait_line(client, "RECOVERY PHRASE")
            _wait_line(client, "dashboard at")
            _stop(client)
            assert client.wait(15) in (0, 130, -signal.SIGTERM)
        finally:
            _stop(client)
    finally:
        _stop(server)


def test_client_restore_phrase_flag(tmp_path):
    """--restore-phrase rebuilds a deterministic identity at first run."""
    from backuwup_tpu.crypto import KeyManager, secret_to_phrase

    keys = KeyManager.generate()
    phrase = secret_to_phrase(keys.root_secret)
    server = _spawn(["server", "--bind", "127.0.0.1:18101",
                     "--db", str(tmp_path / "srv.db")])
    try:
        _wait_line(server, "listening on 127.0.0.1:18101")
        client = _spawn(
            ["client", "--restore-phrase", phrase,
             "--config-dir", str(tmp_path / "cfg"),
             "--data-dir", str(tmp_path / "data"),
             "--server-addr", "127.0.0.1:18101",
             "--ui-bind", "127.0.0.1:0"])
        try:
            _wait_line(client, "dashboard at")
        finally:
            _stop(client)
        # identity persisted deterministically from the phrase
        from backuwup_tpu.store import Store
        store = Store(tmp_path / "cfg")
        assert store.get_root_secret() == keys.root_secret
        store.close()
    finally:
        _stop(server)
