"""Durability invariant monitor (obs/invariants.py).

The monitor's one job is to state whether the restore promise holds
RIGHT NOW from verifier-side state alone, so these tests build placement
tables by hand and check every classification edge: empty store, legacy
whole-file + striped mixes, exactly RS_K clean survivors (degraded, not
violated) vs RS_K - 1 with lost rows (violated), mid-upload incomplete
stripes (degraded, never violated), the dark-peer deadline boundary,
violation-second accrual, orphan detection against the blob index, and
the cross-client registry summary the server /healthz reports.

Plus the histogram quantile estimator the scorecard and obs_dump use.
"""

import math
from dataclasses import replace

import pytest

from backuwup_tpu import defaults
from backuwup_tpu.obs import invariants as inv
from backuwup_tpu.obs import journal as obs_journal
from backuwup_tpu.obs import metrics as obs_metrics
from backuwup_tpu.obs.invariants import InvariantMonitor
from backuwup_tpu.obs.metrics import log_buckets, quantile_from_buckets
from backuwup_tpu.store import Store

K, M = defaults.RS_K, defaults.RS_M
N = K + M
NOW = 1_700_000_000.0


@pytest.fixture(autouse=True)
def _isolate():
    """Zero the process registry and drop any installed journal so tests
    never see each other's durability series."""
    obs_metrics.registry().reset()
    yield
    obs_metrics.registry().reset()
    obs_journal.uninstall()


@pytest.fixture
def store(tmp_path):
    s = Store(tmp_path / "cfg", data_base=tmp_path / "data")
    yield s
    s.close()


def peer(i: int) -> bytes:
    return bytes([0x50 + i]) * 32


def place_stripe(store, pid: bytes, holders, size=4096, now=NOW):
    for idx, p in enumerate(holders):
        store.record_placement(pid, p, size, now=now, shard_index=idx)


def demote(store, p: bytes) -> None:
    store.put_audit_state(replace(store.get_audit_state(p), demoted=True))


# --- sweep classification ---------------------------------------------------


def test_empty_store_sweeps_ok(store):
    rep = InvariantMonitor(store, client="t").sweep(now=NOW)
    assert rep.status == "ok"
    assert rep.stripes_total == 0 and rep.packfiles_total == 0
    assert rep.repair_debt_bytes == 0 and rep.violations == []


def test_clean_mixed_whole_and_striped(store):
    holders = [peer(i) for i in range(N)]
    for p in holders:
        store.add_peer_negotiated(p, 1 << 20, now=NOW)
    place_stripe(store, b"\x01" * 32, holders, now=NOW)
    store.record_placement(b"\x02" * 32, holders[0], 9000, now=NOW)  # whole
    rep = InvariantMonitor(store, client="t").sweep(now=NOW)
    assert rep.status == "ok"
    assert rep.stripes_total == 1
    assert rep.packfiles_total == 2
    assert rep.placements_total == N + 1


def test_exactly_k_clean_survivors_is_degraded_not_violated(store):
    holders = [peer(i) for i in range(N)]
    place_stripe(store, b"\x01" * 32, holders, size=1000, now=NOW)
    for p in holders[:M]:  # lose m -> exactly k clean survive
        demote(store, p)
    rep = InvariantMonitor(store, client="t").sweep(now=NOW)
    assert rep.status == "degraded"
    assert rep.stripes_degraded == 1 and rep.stripes_lost == 0
    assert rep.packfiles_unrestorable == 0
    assert rep.repair_debt_bytes == M * 1000
    assert any("lost shard(s)" in d for d in rep.degradations)


def test_below_k_clean_survivors_is_violated(store):
    holders = [peer(i) for i in range(N)]
    place_stripe(store, b"\x01" * 32, holders, now=NOW)
    for p in holders[:M + 1]:  # k - 1 clean left
        demote(store, p)
    rep = InvariantMonitor(store, client="t").sweep(now=NOW)
    assert rep.status == "violated"
    assert rep.stripes_lost == 1 and rep.packfiles_unrestorable == 1
    assert any("unrestorable" in v for v in rep.violations)


def test_incomplete_stripe_without_loss_never_violates(store):
    # placements land per-ack, so a mid-upload stripe is short rows with
    # nobody lost: that is shrinking margin, not a broken promise
    holders = [peer(i) for i in range(K - 1)]  # fewer than k rows
    place_stripe(store, b"\x01" * 32, holders, now=NOW)
    rep = InvariantMonitor(store, client="t").sweep(now=NOW)
    assert rep.status == "degraded"
    assert rep.stripes_lost == 0 and rep.packfiles_unrestorable == 0
    assert any("incomplete" in d for d in rep.degradations)


def test_live_whole_replica_trumps_stripe_math(store):
    holders = [peer(i) for i in range(N)]
    pid = b"\x01" * 32
    place_stripe(store, pid, holders, now=NOW)
    store.record_placement(pid, peer(10), 9000, now=NOW)  # whole copy
    for p in holders[:N]:  # every shard lost...
        demote(store, p)
    rep = InvariantMonitor(store, client="t").sweep(now=NOW)
    # ...but the whole replica keeps it restorable: degraded (debt), not
    # violated
    assert rep.status == "degraded"
    assert rep.packfiles_unrestorable == 0


def test_whole_packfile_with_every_replica_lost_is_violated(store):
    store.record_placement(b"\x02" * 32, peer(0), 9000, now=NOW)
    demote(store, peer(0))
    rep = InvariantMonitor(store, client="t").sweep(now=NOW)
    assert rep.status == "violated"
    assert rep.packfiles_unrestorable == 1
    assert any("every replica" in v for v in rep.violations)


def test_dark_peer_deadline_boundary(store):
    deadline = defaults.PEER_DARK_DEADLINE_S
    store.record_placement(b"\x02" * 32, peer(0), 9000, now=NOW)
    # last_seen exactly at the deadline: NOT lost (strictly past it is)
    store.add_peer_negotiated(peer(0), 1 << 20, now=NOW - deadline)
    assert inv.lost_peers(store, NOW) == set()
    rep = InvariantMonitor(store, client="t").sweep(now=NOW)
    assert rep.status == "ok"
    # one second past the deadline: lost, and the whole-file placement
    # flips straight to violated
    rep = InvariantMonitor(store, client="t").sweep(now=NOW + 1.0)
    assert inv.lost_peers(store, NOW + 1.0) == {peer(0)}
    assert rep.status == "violated"


def test_violation_seconds_accrue_from_previous_bad_sweep(store):
    store.record_placement(b"\x02" * 32, peer(0), 9000, now=NOW)
    demote(store, peer(0))
    mon = InvariantMonitor(store, client="t")

    def violation_s():
        snap = obs_metrics.registry().snapshot()
        fam = snap.get("bkw_durability_violation_seconds_total")
        return sum(s["value"] for s in fam["series"]) if fam else 0.0

    mon.sweep(now=NOW)        # first bad sweep starts the clock
    assert violation_s() == 0.0
    mon.sweep(now=NOW + 5.0)  # still violated: the interval accrues
    assert violation_s() == pytest.approx(5.0)
    mon.sweep(now=NOW + 7.5)
    assert violation_s() == pytest.approx(7.5)


def test_orphaned_placements_against_blob_index(store):
    class FakeIndex:
        def packfile_ids(self):
            return {b"\x01" * 32}

    holders = [peer(i) for i in range(N)]
    place_stripe(store, b"\x01" * 32, holders, now=NOW)   # referenced
    place_stripe(store, b"\x09" * 32, holders, now=NOW)   # leaked
    rep = InvariantMonitor(store, index=FakeIndex(),
                           client="t").sweep(now=NOW)
    assert rep.orphaned_placements == N
    assert rep.status == "degraded"
    assert any("orphaned" in d for d in rep.degradations)


def test_audit_coverage_age_from_placement_then_ledger(store):
    max_age = defaults.DURABILITY_AUDIT_MAX_AGE_S
    holders = [peer(i) for i in range(N)]
    place_stripe(store, b"\x01" * 32, holders, now=NOW - max_age - 60)
    # never audited: age counts from first placement and is past the cap
    rep = InvariantMonitor(store, client="t").sweep(now=NOW)
    assert rep.audit_coverage_age_s == pytest.approx(max_age + 60)
    assert any("stalest audit" in d for d in rep.degradations)
    # a fresh attestation for every holder resets the age
    for p in holders:
        store.put_audit_state(replace(store.get_audit_state(p),
                                      last_audit=NOW - 1.0))
    rep = InvariantMonitor(store, client="t").sweep(now=NOW)
    assert rep.audit_coverage_age_s == pytest.approx(1.0)
    assert rep.status == "ok"


def test_summary_from_registry_sums_clients_and_takes_worst_status(
        store, tmp_path):
    other = Store(tmp_path / "cfg2", data_base=tmp_path / "data2")
    try:
        holders = [peer(i) for i in range(N)]
        place_stripe(store, b"\x01" * 32, holders, now=NOW)
        place_stripe(other, b"\x02" * 32, holders, now=NOW)
        for p in holders[:M]:
            demote(other, p)
        InvariantMonitor(store, client="a").sweep(now=NOW)
        InvariantMonitor(other, client="b").sweep(now=NOW)
        summary = inv.summary_from_registry()
        assert summary["stripes_total"] == 2     # summed across clients
        assert summary["stripes_degraded"] == 1  # only client b's
        assert summary["status"] == "degraded"   # the worst of ok/degraded
    finally:
        other.close()


def test_fresh_registry_summary_is_ok_zeros():
    summary = inv.summary_from_registry()
    assert summary["status"] == "ok"
    assert summary["stripes_total"] == 0


# --- histogram quantile estimation (scorecard + obs_dump) -------------------


def test_quantile_from_buckets_empty_is_nan():
    assert math.isnan(quantile_from_buckets([0.1, 1.0], [0, 0, 0], 0.5))


def test_quantile_from_buckets_interpolates_geometrically():
    bounds = [1.0, 2.0]
    # all mass in the (1, 2] bucket: p50 sits at the geometric midpoint
    assert quantile_from_buckets(bounds, [0, 10, 0], 0.5) == \
        pytest.approx(math.sqrt(2.0))
    # first bucket has no lower edge: linear within (0, 1]
    assert quantile_from_buckets(bounds, [10, 0, 0], 0.5) == \
        pytest.approx(0.5)


def test_quantile_from_buckets_overflow_clamps_to_last_bound():
    assert quantile_from_buckets([1.0, 2.0], [0, 0, 7], 0.99) == 2.0


def test_histogram_quantile_method_per_series():
    h = obs_metrics.histogram("t_q_seconds", "t", ("op",),
                              buckets=log_buckets(0.001, 2.0, 12))
    for _ in range(100):
        h.observe(0.5, op="x")
    p50 = h.quantile(0.5, op="x")
    assert 0.25 <= p50 <= 1.0      # within the 0.5-containing bucket
    assert math.isnan(h.quantile(0.5, op="missing"))
