"""Coordination server + client control plane over loopback HTTP/WS."""

import asyncio

import pytest

from backuwup_tpu.crypto import KeyManager
from backuwup_tpu.net.client import ServerClient, ServerError
from backuwup_tpu.net.server import CoordinationServer
from backuwup_tpu.store import Store


@pytest.fixture
def loop():
    loop = asyncio.new_event_loop()
    yield loop
    loop.close()


def _client(tmp_path, name, port):
    keys = KeyManager.from_secret(bytes([len(name)]) * 31 + name.encode()[:1])
    store = Store(tmp_path / name)
    return ServerClient(keys, store, addr=f"127.0.0.1:{port}")


def test_register_login_and_session(tmp_path, loop):
    async def run():
        server = CoordinationServer()
        port = await server.start()
        c = _client(tmp_path, "a", port)
        await c.register()
        token = await c.login()
        assert len(token) == 16
        # authenticated call works
        await c.backup_done(b"\x01" * 32)
        assert server.db.get_latest_client_snapshot(c.keys.client_id) == b"\x01" * 32
        # corrupt token: transparent re-login
        c.store.set_auth_token(b"\x00" * 16)
        await c.backup_done(b"\x02" * 32)
        assert server.db.get_latest_client_snapshot(c.keys.client_id) == b"\x02" * 32
        await c.close()
        await server.stop()
    loop.run_until_complete(run())


def test_login_unknown_client_rejected(tmp_path, loop):
    async def run():
        server = CoordinationServer()
        port = await server.start()
        c = _client(tmp_path, "b", port)
        with pytest.raises(ServerError):
            await c.login()
        await c.close()
        await server.stop()
    loop.run_until_complete(run())


def test_storage_request_matching(tmp_path, loop):
    """Two online clients with similar requests get matched both ways
    (backup_request.rs:73-185)."""
    async def run():
        server = CoordinationServer()
        port = await server.start()
        a = _client(tmp_path, "a", port)
        b = _client(tmp_path, "c", port)
        matched_a, matched_b = [], []

        async def on_a(msg):
            matched_a.append(msg)

        async def on_b(msg):
            matched_b.append(msg)

        for c, cb in ((a, on_a), (b, on_b)):
            await c.register()
            await c.login()
            c.on_backup_matched = cb
            c.start_ws()
            await asyncio.wait_for(c.ws_connected.wait(), 5)

        await a.backup_storage_request(100 * 1000 * 1000)
        assert server.queue.pending() == 1
        await b.backup_storage_request(60 * 1000 * 1000)
        await asyncio.sleep(0.3)
        # b's 60MB fully matched; a keeps 40MB queued
        assert len(matched_a) == 1 and len(matched_b) == 1
        assert matched_a[0].destination_id == b.keys.client_id
        assert matched_a[0].storage_available == 60 * 1000 * 1000
        assert matched_b[0].destination_id == a.keys.client_id
        assert server.queue.pending() == 1
        # ledger recorded both directions
        assert server.db.get_client_negotiated_peers(a.keys.client_id) == \
            [b.keys.client_id]
        assert server.db.get_client_negotiated_peers(b.keys.client_id) == \
            [a.keys.client_id]
        await a.close()
        await b.close()
        await server.stop()
    loop.run_until_complete(run())


def test_matcher_requester_offline_does_not_drain_queue(loop):
    """If the requester's push fails mid-fulfill, matching must stop:
    the already-notified candidate's match stays recorded (a client is
    never notified of a match the server does not persist), and the
    remaining candidates are never popped."""
    from backuwup_tpu.net.server import ServerDB, StorageQueue

    req = b"\x0a" * 32
    cands = [bytes([i + 1]) * 32 for i in range(3)]

    class FakeConnections:
        def is_online(self, client_id):
            return True

        async def notify(self, client_id, msg):
            return bytes(client_id) != req  # requester unreachable

    db = ServerDB(":memory:")
    q = StorageQueue(db, FakeConnections())

    # seed the queue directly: calling fulfill() repeatedly would pair the
    # candidates with each other before the requester arrives
    import time as _time
    for c in cands:
        q._queue.append((c, 50 * 1000 * 1000, _time.time() + 300))

    loop.run_until_complete(q.fulfill(req, 150 * 1000 * 1000))
    # the first candidate was fully matched (and notified, so the record
    # stays); the other two were never popped
    assert q.pending() == 2
    assert db.get_client_negotiated_peers(req) == [cands[0]]
    assert db.get_client_negotiated_peers(cands[0]) == [req]
    for c in cands[1:]:
        assert db.get_client_negotiated_peers(c) == []


def test_matcher_offline_candidate_skipped(loop):
    """A candidate whose push fails is dropped; the next one matches and
    both sides are recorded (backup_request.rs:166-173)."""
    from backuwup_tpu.net.server import ServerDB, StorageQueue

    req = b"\x0a" * 32
    dead, alive = b"\x01" * 32, b"\x02" * 32

    class FakeConnections:
        def is_online(self, client_id):
            return True

        async def notify(self, client_id, msg):
            return bytes(client_id) != dead

    db = ServerDB(":memory:")
    q = StorageQueue(db, FakeConnections())

    async def run():
        await q.fulfill(dead, 50 * 1000 * 1000)
        await q.fulfill(alive, 50 * 1000 * 1000)
        await q.fulfill(req, 50 * 1000 * 1000)

    loop.run_until_complete(run())
    assert q.pending() == 0
    assert db.get_client_negotiated_peers(req) == [alive]
    assert db.get_client_negotiated_peers(alive) == [req]
    assert db.get_client_negotiated_peers(dead) == []


def test_oversized_storage_request_rejected(tmp_path, loop):
    async def run():
        server = CoordinationServer()
        port = await server.start()
        a = _client(tmp_path, "a", port)
        await a.register()
        await a.login()
        with pytest.raises(ServerError):
            await a.backup_storage_request(17 << 30)  # > 16 GiB cap
        await a.close()
        await server.stop()
    loop.run_until_complete(run())


def test_p2p_rendezvous_relay(tmp_path, loop):
    """begin/confirm relays IncomingP2PConnection + FinalizeP2PConnection
    (handlers/p2p_connection_request.rs)."""
    async def run():
        server = CoordinationServer()
        port = await server.start()
        a = _client(tmp_path, "a", port)
        b = _client(tmp_path, "c", port)
        incoming_b, finalize_a = [], []

        async def on_incoming(msg):
            incoming_b.append(msg)

        async def on_finalize(msg):
            finalize_a.append(msg)

        a.on_finalize_p2p = on_finalize
        b.on_incoming_p2p = on_incoming
        for c in (a, b):
            await c.register()
            await c.login()
            c.start_ws()
            await asyncio.wait_for(c.ws_connected.wait(), 5)

        nonce = b"\x07" * 16
        await a.p2p_connection_begin(b.keys.client_id, nonce)
        await asyncio.sleep(0.2)
        assert len(incoming_b) == 1
        assert incoming_b[0].source_client_id == a.keys.client_id
        assert incoming_b[0].session_nonce == nonce

        await b.p2p_connection_confirm(a.keys.client_id, "127.0.0.1:45678")
        await asyncio.sleep(0.2)
        assert len(finalize_a) == 1
        assert finalize_a[0].destination_client_id == b.keys.client_id
        assert finalize_a[0].destination_ip_address == "127.0.0.1:45678"

        # relay to an offline destination errors
        ghost = KeyManager.from_secret(b"\x0f" * 32)
        with pytest.raises(ServerError):
            await a.p2p_connection_begin(ghost.client_id, nonce)
        await a.close()
        await b.close()
        await server.stop()
    loop.run_until_complete(run())


def test_restore_info(tmp_path, loop):
    async def run():
        server = CoordinationServer()
        port = await server.start()
        a = _client(tmp_path, "a", port)
        await a.register()
        await a.login()
        from backuwup_tpu.net.client import NoBackups
        with pytest.raises(NoBackups):
            await a.backup_restore()
        await a.backup_done(b"\x05" * 32)
        server.db.save_storage_negotiated(a.keys.client_id, b"\x09" * 32, 100)
        info = await a.backup_restore()
        assert info.snapshot_hash == b"\x05" * 32
        assert info.peers == [(b"\x09" * 32).hex()]
        await a.close()
        await server.stop()
    loop.run_until_complete(run())
