"""Transport-envelope proof: no legal packfile or index file can exceed
one signed P2P message (the reference proves its analog statically in
pack.rs:257-288 validate_size_constraints; here the transport cap (8 MiB,
p2p_message.rs:8) is SMALLER than the packfile format cap (16 MiB), so the
writer's effective cap must be the wire max minus envelope overhead)."""

import random

from backuwup_tpu import defaults, wire
from backuwup_tpu.crypto import KeyManager
from backuwup_tpu.net.p2p import _sign_body
from backuwup_tpu.snapshot.blob_index import BlobIndex
from backuwup_tpu.snapshot.packfile import PackfileWriter
from backuwup_tpu.wire import Blob, BlobKind


def test_envelope_overhead_margin():
    """A maximum-payload FILE message, signed and framed, fits the wire
    cap — i.e. P2P_ENVELOPE_OVERHEAD covers the real encoding."""
    keys = KeyManager.from_secret(b"\x41" * 32)
    payload = b"\xaa" * defaults.PACKFILE_WIRE_MAX
    body = wire.P2PBody(
        kind=wire.P2PBodyKind.FILE,
        header=wire.P2PHeader(sequence_number=1,
                              session_nonce=b"\x07" * 16),
        file_info=wire.FileInfoKind.PACKFILE,
        file_id=b"\x01" * 12,
        data=payload)
    raw = _sign_body(keys, body)
    assert len(raw) <= defaults.MAX_P2P_MESSAGE_SIZE
    # and the margin is not absurdly loose either (stays within 2x of the
    # declared overhead so drift gets noticed)
    assert len(raw) - len(payload) <= defaults.P2P_ENVELOPE_OVERHEAD


def test_worst_case_packfile_static_bound():
    """Analytic worst case, mirroring validate_size_constraints: a file
    flushed at the projected-size check can never exceed the wire cap."""
    keys = KeyManager.from_secret(b"\x42" * 32)
    w = PackfileWriter(keys, "/tmp/unused")
    cap = min(defaults.PACKFILE_MAX_SIZE, defaults.PACKFILE_WIRE_MAX)
    # add_blob flushes BEFORE appending whenever the projected size would
    # cross the cap, and rejects single records that exceed it; therefore
    # the largest possible written file is `cap` exactly.  Check the
    # arithmetic the guard relies on for the worst legal single record:
    max_chunk = defaults.CDC_MAX_CHUNK
    # zstd worst case for incompressible input is bounded; the writer
    # stores whichever of (raw, compressed) is smaller plus AES overhead
    worst_record = 12 + 16 + max_chunk + 1024  # nonce + tag + data + slack
    assert w._file_size(1, worst_record) <= cap
    # ... and the max-entry header alone cannot blow the cap when records
    # are tiny: N tiny blobs flush by the same projected-size rule
    n_max = (cap - w._FILE_OVERHEAD) // w._HEADER_ENTRY
    assert w._file_size(n_max, 0) <= cap


def test_adversarial_packfiles_fit_one_message(tmp_path):
    """Incompressible max-size chunks through the real writer: every file
    on disk + its signed envelope fits MAX_P2P_MESSAGE_SIZE."""
    keys = KeyManager.from_secret(b"\x43" * 32)
    rng = random.Random(99)
    sizes = []
    writer = PackfileWriter(
        keys, tmp_path / "pack",
        on_packfile=lambda pid, path, hashes, size: sizes.append(
            (path, size)))
    for i in range(7):
        data = rng.randbytes(defaults.CDC_MAX_CHUNK)  # incompressible
        from backuwup_tpu.ops.blake3_cpu import blake3_hash
        writer.add_blob(Blob(hash=blake3_hash(data),
                             kind=BlobKind.FILE_CHUNK, data=data))
    writer.flush()
    assert sizes, "no packfiles written"
    for path, size in sizes:
        raw = _sign_body(keys, wire.P2PBody(
            kind=wire.P2PBodyKind.FILE,
            header=wire.P2PHeader(sequence_number=1,
                                  session_nonce=b"\x07" * 16),
            file_info=wire.FileInfoKind.PACKFILE,
            file_id=b"\x01" * 12,
            data=path.read_bytes()))
        assert len(raw) <= defaults.MAX_P2P_MESSAGE_SIZE, size


def test_index_files_fit_one_message(tmp_path):
    """A full 50k-entry index file + envelope fits the wire cap
    (blob_index.rs:16 sizing)."""
    keys = KeyManager.from_secret(b"\x44" * 32)
    index = BlobIndex(keys, tmp_path / "index")
    rng = random.Random(7)
    for i in range(defaults.INDEX_FILE_MAX_ENTRIES):
        index.mark_queued(rng.randbytes(32))
    # finalize everything into one packfile id so flush writes full files
    index.finalize_packfile(b"\x01" * 12, list(index._queued))
    paths = index.flush()
    assert paths
    for path in paths:
        raw = _sign_body(keys, wire.P2PBody(
            kind=wire.P2PBodyKind.FILE,
            header=wire.P2PHeader(sequence_number=1,
                                  session_nonce=b"\x07" * 16),
            file_info=wire.FileInfoKind.INDEX,
            file_id=(0).to_bytes(8, "little"),
            data=path.read_bytes()))
        assert len(raw) <= defaults.MAX_P2P_MESSAGE_SIZE
