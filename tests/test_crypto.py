"""Key derivation determinism, signatures, recovery-phrase round trip."""

import pytest

from backuwup_tpu.crypto import (
    KeyManager,
    hkdf_derive,
    parse_recovery,
    phrase_to_secret,
    secret_to_phrase,
    secret_to_words,
    verify_signature,
    words_to_secret,
)


def test_deterministic_derivation():
    secret = bytes(range(32))
    a = KeyManager.from_secret(secret)
    b = KeyManager.from_secret(secret)
    assert a.client_id == b.client_id
    assert a.backup_secret == b.backup_secret
    assert len(a.client_id) == 32 and len(a.backup_secret) == 32
    # identity and backup key material must differ
    assert a.backup_secret != secret


def test_distinct_secrets_distinct_identities():
    a = KeyManager.from_secret(b"\x01" * 32)
    b = KeyManager.from_secret(b"\x02" * 32)
    assert a.client_id != b.client_id


def test_sign_verify():
    km = KeyManager.from_secret(bytes(range(32)))
    msg = b"storage request 12345"
    sig = km.sign(msg)
    assert verify_signature(km.client_id, msg, sig)
    assert not verify_signature(km.client_id, msg + b"x", sig)
    other = KeyManager.from_secret(b"\x05" * 32)
    assert not verify_signature(other.client_id, msg, sig)


def test_derive_backup_key_contexts():
    km = KeyManager.from_secret(bytes(range(32)))
    header = km.derive_backup_key(b"header")
    index = km.derive_backup_key(b"index")
    blob = km.derive_backup_key(b"\xaa" * 32)
    assert len({header, index, blob}) == 3
    assert km.derive_backup_key(b"header") == header
    assert hkdf_derive(km.backup_secret, b"header") == header


def test_phrase_round_trip():
    secret = bytes(range(32))
    phrase = secret_to_phrase(secret)
    assert phrase_to_secret(phrase) == secret
    # forgiveness: case and confusable characters
    assert phrase_to_secret(phrase.upper().replace("1", "l")) == secret


def test_phrase_rejects_typos():
    phrase = secret_to_phrase(bytes(range(32)))
    corrupted = ("7" if phrase[0] != "7" else "8") + phrase[1:]
    with pytest.raises(ValueError):
        phrase_to_secret(corrupted)
    with pytest.raises(ValueError):
        phrase_to_secret(phrase[:-9])


def test_generate_restores_from_phrase():
    km = KeyManager.generate()
    restored = KeyManager.from_secret(phrase_to_secret(secret_to_phrase(km.root_secret)))
    assert restored.client_id == km.client_id


def test_wordlist_shape():
    from backuwup_tpu.wordlist import WORD_INDEX, WORDS
    assert len(WORDS) == 2048
    assert len(WORD_INDEX) == 2048  # no duplicates
    assert all(w.isalpha() and w.islower() and 3 <= len(w) <= 8
               for w in WORDS)


def test_word_phrase_round_trip():
    for secret in (bytes(range(32)), b"\x00" * 32, b"\xff" * 32,
                   KeyManager.generate().root_secret):
        words = secret_to_words(secret)
        assert len(words.split()) == 24
        assert words_to_secret(words) == secret
        # forgiveness: case, dashes, 4-char prefixes where unambiguous
        assert words_to_secret(words.upper().replace(" ", " - ")) == secret


def test_word_phrase_prefix_tolerance():
    secret = bytes(range(32))
    words = secret_to_words(secret).split()
    from backuwup_tpu.wordlist import WORDS
    trunc = []
    for w in words:
        pre = w[:4]
        trunc.append(pre if sum(x.startswith(pre) for x in WORDS) == 1 else w)
    assert words_to_secret(" ".join(trunc)) == secret


def test_word_phrase_rejects_typos():
    secret = bytes(range(32))
    words = secret_to_words(secret).split()
    swapped = [words[1], words[0]] + words[2:]
    if swapped != words:
        with pytest.raises(ValueError):
            words_to_secret(" ".join(swapped))
    with pytest.raises(ValueError):
        words_to_secret(" ".join(words[:-1]))
    with pytest.raises(ValueError):
        words_to_secret(" ".join(["zzzzz"] + words[1:]))


def test_parse_recovery_accepts_both_forms():
    secret = KeyManager.generate().root_secret
    assert parse_recovery(secret_to_phrase(secret)) == secret
    assert parse_recovery(secret_to_words(secret)) == secret
    with pytest.raises(ValueError):
        parse_recovery("not a recovery phrase at all")


def test_truncated_phrase_flags_exact_prefix_words_ambiguous():
    """In truncation-style entry, a word that is both a list word AND a
    proper prefix of longer list words (bell/belly) is ambiguous — the
    transcriber may have cut either word down to it."""
    words = ["bell"] + ["zebra"] * 22 + ["abst"]  # 'abst' -> abstract only
    with pytest.raises(ValueError, match="ambiguous word 'bell'"):
        words_to_secret(" ".join(words))
    # the same word in a FULLY-spelled phrase resolves exactly (wrong
    # checksum here, but resolution must get that far)
    full = ["bell"] + ["zebra"] * 23
    with pytest.raises(ValueError, match="checksum"):
        words_to_secret(" ".join(full))


def test_foreign_wordlist_phrase_gets_actionable_error():
    """A 24-word BIP39 phrase from another wallet/language names the
    incompatibility instead of a bare 'unknown word'."""
    with pytest.raises(ValueError, match="cannot be imported"):
        words_to_secret("abeja " * 24)  # Spanish BIP39 word
    with pytest.raises(ValueError, match="cannot be imported"):
        parse_recovery("abeja " * 24)  # surfaced through either-form parse


def test_valid_foreign_words_fail_checksum_with_guidance():
    """All-valid words in a foreign layout die on the checksum with a
    message explaining the incompatibility."""
    secret = bytes(range(32))
    words = secret_to_words(secret).split()
    swapped = " ".join([words[1], words[0]] + words[2:])
    assert swapped != " ".join(words)
    with pytest.raises(ValueError, match="another wallet"):
        words_to_secret(swapped)
