"""Send-pipeline unit tests (engine.py) against fake transports.

Regression coverage for the index-file watermark semantics: a retry after a
mid-batch P2P failure must never re-send files the peer already acked
(the peer's writer refuses overwrites — resending livelocks; reference
send.rs re-checks highest_sent_index per file).
"""

import asyncio

import pytest

from backuwup_tpu import wire
from backuwup_tpu.crypto import KeyManager
from backuwup_tpu.engine import Engine, Orchestrator
from backuwup_tpu.net.p2p import P2PError
from backuwup_tpu.store import Store


class FlakyTransport:
    """Records sent index numbers; raises P2PError per a failure plan."""

    def __init__(self, fail_on: set):
        self.fail_on = set(fail_on)
        self.sent = []

    async def send_data(self, data, kind, file_id):
        assert kind == wire.FileInfoKind.INDEX
        num = int.from_bytes(file_id, "little")
        if num in self.fail_on:
            self.fail_on.discard(num)  # fail once, then succeed on retry
            raise P2PError(f"injected failure on index {num}")
        if num in self.sent:
            raise AssertionError(
                f"index file {num} re-sent after ack (livelock bug)")
        self.sent.append(num)

    async def close(self):
        pass


@pytest.fixture
def engine(tmp_path):
    keys = KeyManager.generate()
    store = Store(directory=tmp_path / "cfg", data_base=tmp_path / "data")
    eng = Engine(keys, store, server=None, node=None)
    yield eng
    store.close()


def test_index_send_refilters_by_watermark_after_midbatch_failure(engine):
    idx_dir = engine._index_dir()
    for i in range(3):
        (idx_dir / str(i)).write_bytes(b"index-%d" % i)

    transport = FlakyTransport(fail_on={1})
    peer = b"\x01" * 32

    async def fake_get_peer(orch, estimate, fulfilled, last_request):
        return transport, peer, 1 << 30

    engine._get_peer_connection = fake_get_peer
    orch = Orchestrator()

    async def run():
        await asyncio.wait_for(
            engine._send_index_files(orch, 0, 0), timeout=10)

    asyncio.new_event_loop().run_until_complete(run())
    # 0 sent, 1 failed once then retried, 2 sent — each exactly once
    assert transport.sent == [0, 1, 2]
    assert engine.store.get_highest_sent_index() == 2


def test_index_send_numeric_order_with_ten_plus_files(engine):
    """11+ index files must go in numeric order (lexicographic Path order
    would send '10' before '2', regressing the watermark and skipping
    files on retry)."""
    idx_dir = engine._index_dir()
    for i in range(12):
        (idx_dir / str(i)).write_bytes(b"x")

    transport = FlakyTransport(fail_on={10})
    peer = b"\x03" * 32

    async def fake_get_peer(orch, estimate, fulfilled, last_request):
        return transport, peer, 1 << 30

    engine._get_peer_connection = fake_get_peer

    async def run():
        await asyncio.wait_for(
            engine._send_index_files(Orchestrator(), 0, 0), timeout=10)

    asyncio.new_event_loop().run_until_complete(run())
    assert transport.sent == list(range(12))
    assert engine.store.get_highest_sent_index() == 11


def test_watermark_is_monotonic(engine):
    engine.store.set_highest_sent_index(7)
    engine.store.set_highest_sent_index(3)  # must not regress
    assert engine.store.get_highest_sent_index() == 7


def test_index_send_skips_already_watermarked(engine):
    idx_dir = engine._index_dir()
    for i in range(4):
        (idx_dir / str(i)).write_bytes(b"x")
    engine.store.set_highest_sent_index(1)

    transport = FlakyTransport(fail_on=set())
    peer = b"\x02" * 32

    async def fake_get_peer(orch, estimate, fulfilled, last_request):
        return transport, peer, 1 << 30

    engine._get_peer_connection = fake_get_peer

    async def run():
        await asyncio.wait_for(
            engine._send_index_files(Orchestrator(), 0, 0), timeout=10)

    asyncio.new_event_loop().run_until_complete(run())
    assert transport.sent == [2, 3]

class PackfileTransport:
    """Records packfile sends in order."""

    def __init__(self):
        self.sent = []

    async def send_data(self, data, kind, file_id):
        assert kind == wire.FileInfoKind.PACKFILE
        self.sent.append(bytes(file_id))

    async def send_file(self, data, kind, file_id, *, resume=True,
                        throughput_bps=0.0, progress=None):
        # sub-chunk payloads ride the legacy frame, like the real
        # Transport.send_file
        await self.send_data(data, kind, file_id)

    async def close(self):
        pass


def test_send_loop_skips_oversized_packfile_not_stops(engine, monkeypatch):
    """ADVICE r3 (medium): a large packfile sorting FIRST in directory
    order must not starve a smaller one that fits the peer — the loop
    skips files that don't fit instead of breaking, otherwise the same
    almost-full peer is re-dialed forever."""
    from backuwup_tpu import defaults

    monkeypatch.setattr(defaults, "PEER_OVERUSE_GRACE", 0)

    pack_dir = engine._pack_dir()
    big_id, small_id = b"\xaa" * 12, b"\xbb" * 12
    (pack_dir / "aa").mkdir(parents=True)
    (pack_dir / "bb").mkdir(parents=True)
    (pack_dir / "aa" / big_id.hex()).write_bytes(b"B" * 10_000)
    (pack_dir / "bb" / small_id.hex()).write_bytes(b"s" * 1_000)

    transport = PackfileTransport()
    peer = b"\x04" * 32
    calls = {"n": 0}

    async def fake_get_peer(orch, estimate, fulfilled, last_request,
                            min_free=1):
        calls["n"] += 1
        # first acquisition: peer only has room for the small file;
        # afterwards plenty, so the loop can finish
        return transport, peer, (2_000 if calls["n"] == 1 else 1 << 30)

    engine._get_peer_connection = fake_get_peer
    orch = Orchestrator()
    orch.packing_completed = True
    orch.buffer_bytes = 11_000

    async def run():
        await asyncio.wait_for(engine._send_loop(orch, 0), timeout=10)

    asyncio.new_event_loop().run_until_complete(run())
    # the small file went out on the FIRST peer (no livelock), the big one
    # on the second acquisition
    assert transport.sent == [small_id, big_id]
