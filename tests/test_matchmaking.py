"""Sharded matchmaking + write-behind server store (net/matchmaking.py,
net/serverstore.py — the PR-10 scale-out of the coordination plane).

Covers the satellites:

* the latent ServerDB thread-safety hole (one sqlite connection shared
  across request threads with ``check_same_thread=False`` and no
  serialization) — both store modes are hammered from many threads;
* write-behind group commit: many concurrent writes, few commits, all
  durable, and every commit on the single writer thread;
* matchmaking semantics parity on the sharded tier (audit-block,
  rollback on candidate push failure, re-enqueue on requester push
  failure), cross-shard work stealing, fairness under a large request
  queued behind many small ones, and O(log n) deadline-heap expiry.
"""

import asyncio
import threading
import time

import pytest

from backuwup_tpu import defaults
from backuwup_tpu.net.matchmaking import ShardedMatchmaker
from backuwup_tpu.net.serverstore import (_COMMITS, ServerDB,
                                          SqliteServerStore)

MIB = 1 << 20


def pk(i: int) -> bytes:
    """Pubkey whose home shard is ``i % shards`` (the shard key is the
    first 8 bytes big-endian mod N)."""
    return i.to_bytes(8, "big") + bytes(24)


class StubConns:
    """Connection registry double: scripted offline sets, scripted push
    failures, and a per-client log of delivered matches."""

    def __init__(self):
        self.offline = set()
        self.fail_notify = set()
        self.notified = {}

    def is_online(self, client_id) -> bool:
        return bytes(client_id) not in self.offline

    async def notify(self, client_id, msg) -> bool:
        await asyncio.sleep(0)
        if bytes(client_id) in self.fail_notify:
            return False
        self.notified.setdefault(bytes(client_id), []).append(msg)
        return True

    def count(self, client_id) -> int:
        return len(self.notified.get(bytes(client_id), []))


@pytest.fixture
def loop():
    loop = asyncio.new_event_loop()
    yield loop
    loop.close()


# --- store thread-safety + group commit ------------------------------------


@pytest.mark.parametrize("mode", ["write_behind", "direct"])
def test_concurrent_writers_hammer(tmp_path, mode):
    """The legacy ServerDB shared one sqlite connection across request
    threads unserialized; the store now either funnels every op through
    the single writer thread (write-behind) or serializes inline ops
    under a lock (direct).  50 writes from each of 8 threads must all
    land, with no lost updates and no sqlite thread errors."""
    store = (SqliteServerStore(str(tmp_path / "s.db"))
             if mode == "write_behind"
             else ServerDB(str(tmp_path / "d.db")))
    threads, per_thread = 8, 50
    errors = []

    def slam(t: int) -> None:
        try:
            for i in range(per_thread):
                store.save_storage_negotiated(pk(t), pk(1000 + t * per_thread + i), MIB)
        except Exception as e:  # pragma: no cover - the regression
            errors.append(repr(e))

    try:
        ts = [threading.Thread(target=slam, args=(t,))
              for t in range(threads)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=30)
        assert not errors, errors
        for t in range(threads):
            peers = store.get_client_negotiated_peers(pk(t))
            assert len(peers) == per_thread
        if mode == "write_behind":
            # every commit ran on the one writer thread, never here
            assert threading.get_ident() not in store.commit_threads
            assert len(store.commit_threads) == 1
    finally:
        store.close()


def test_group_commit_batches_writes(tmp_path):
    """A burst of writes submitted faster than fsync must coalesce into
    far fewer commits than writes — and still all be readable after
    ``flush()`` (the durability barrier resolves futures post-commit)."""
    store = SqliteServerStore(str(tmp_path / "s.db"))
    writes = 300
    before = _COMMITS.value(mode="group")
    try:
        futs = [store._submit(store._op_save_storage_negotiated,
                              (pk(1), pk(100 + i), MIB))
                for i in range(writes)]
        store.flush()
        for f in futs:
            f.result(timeout=10)
        commits = _COMMITS.value(mode="group") - before
        assert commits >= 1
        assert commits <= writes / 2, \
            f"{commits} commits for {writes} writes: no batching"
        assert len(store.get_client_negotiated_peers(pk(1))) == writes
    finally:
        store.close()


def test_store_readable_after_close(tmp_path):
    """``close()`` stops the writer but keeps the connection for reads
    (the server's stop path reads schema_version for its final log)."""
    store = SqliteServerStore(str(tmp_path / "s.db"))
    store.save_storage_negotiated(pk(1), pk(2), MIB)
    store.close()
    assert len(store.get_client_negotiated_peers(pk(1))) == 1
    store.close()  # idempotent


def test_close_during_write_behind_drains_queue(tmp_path):
    """Regression guard for the shutdown seam: a write accepted by the
    write-behind queue must be either durably committed or loudly
    failed BEFORE ``close()`` returns — a future silently left pending
    is a write the caller was told nothing about.  (The server's stop
    path closes the store while handlers may just have enqueued.)"""
    store = SqliteServerStore(str(tmp_path / "s.db"))
    writes = 200
    futs = [store._submit(store._op_register_client, (pk(i),))
            for i in range(writes)]
    store.close()
    pending = [f for f in futs if not f.done()]
    assert not pending, f"{len(pending)} futures left pending after close()"
    for f in futs:
        f.result(timeout=0)  # raises if any write failed silently
    for i in range(writes):
        assert store.client_exists(pk(i))
    # post-close writes still land via the inline fallback, immediately
    # durable (close flips to direct commits, it does not drop writes)
    store._submit(store._op_register_client, (pk(writes),)).result(timeout=0)
    assert store.client_exists(pk(writes))


# --- sharded matchmaking ----------------------------------------------------


def _mm(store, conns, shards=4, expiry_s=60.0):
    return ShardedMatchmaker(store, conns, expiry_s=expiry_s, shards=shards)


def test_cross_shard_work_stealing(tmp_path, loop):
    """A queued request homed on one shard is matched by a requester
    homed on another: the ring walk visits every shard."""
    store = SqliteServerStore(str(tmp_path / "s.db"))
    conns = StubConns()
    mm = _mm(store, conns, shards=4)
    try:
        async def run():
            await mm.fulfill(pk(1), MIB)       # home shard 1: queues
            assert mm.pending() == 1
            await mm.fulfill(pk(2), MIB)       # home shard 2: steals it
            assert mm.pending() == 0

        loop.run_until_complete(run())
        assert conns.count(pk(1)) == 1 and conns.count(pk(2)) == 1
        assert len(store.get_client_negotiated_peers(pk(1))) == 1
    finally:
        store.close()


def test_large_request_behind_many_small_still_fulfills(tmp_path, loop):
    """Fairness: a large queued request sitting behind many small ones
    (across all shards) is not starved — incoming requesters drain the
    small entries and then the large one, in pieces."""
    store = SqliteServerStore(str(tmp_path / "s.db"))
    conns = StubConns()
    mm = _mm(store, conns, shards=4)
    small_ids = [pk(i) for i in range(10, 22)]
    big = pk(5)
    try:
        async def run():
            # small requests arrive first (they pair off with each other
            # as they come; any leftover stays queued ahead of big)
            for cid in small_ids:
                await mm.fulfill(cid, MIB)
            await mm.fulfill(big, 8 * MIB)  # queues behind the backlog
            assert any(e[0] == big for s in mm.shards
                       for e in s.entries.values())
            # requesters keep arriving; the big entry must drain too
            for i in range(40):
                if not any(e[0] == big for s in mm.shards
                           for e in s.entries.values()):
                    break
                await mm.fulfill(pk(100 + i), MIB)
            assert not any(e[0] == big for s in mm.shards
                           for e in s.entries.values()), "big entry starved"
            assert conns.count(big) >= 1

        loop.run_until_complete(run())
    finally:
        store.close()


def test_deadline_heap_expiry_is_olog(tmp_path, loop):
    """Expiry pops the deadline heap exactly once per expired entry —
    never a rescan of live entries: ``reap_ops`` equals the expired
    count and stays flat across repeated ``pending()`` calls."""
    store = SqliteServerStore(str(tmp_path / "s.db"))
    mm = _mm(store, StubConns(), shards=2, expiry_s=60.0)
    try:
        now = time.time()
        for i in range(50):  # expire almost immediately
            mm.shards[i % 2].add(i, pk(i), MIB, now + 0.01)
        for i in range(50, 60):  # live for the whole test
            mm.shards[i % 2].add(i, pk(i), MIB, now + 60.0)
        time.sleep(0.03)
        assert mm.pending() == 10
        assert mm.reap_ops() == 50
        for _ in range(5):  # repeated sweeps do no per-entry work
            assert mm.pending() == 10
        assert mm.reap_ops() == 50
    finally:
        store.close()


def test_audit_blocked_candidate_dropped(tmp_path, loop):
    """A queued candidate reported failing by >= the block threshold of
    DISTINCT reporters is dropped at pop, not matched."""
    store = SqliteServerStore(str(tmp_path / "s.db"))
    conns = StubConns()
    mm = _mm(store, conns)
    bad, requester = pk(1), pk(2)
    try:
        async def run():
            await mm.fulfill(bad, MIB)  # queues
            for r in range(defaults.AUDIT_SERVER_BLOCK_FAILURES):
                await store.aio.save_audit_report(pk(50 + r), bad, False, "")
            await mm.fulfill(requester, MIB)

        loop.run_until_complete(run())
        assert conns.count(bad) == 0
        assert len(store.get_client_negotiated_peers(requester)) == 0
        # the requester could not match and is queued itself
        assert mm.pending() == 1
    finally:
        store.close()


def test_candidate_push_failure_rolls_back(tmp_path, loop):
    store = SqliteServerStore(str(tmp_path / "s.db"))
    conns = StubConns()
    mm = _mm(store, conns)
    dead, requester = pk(1), pk(2)
    conns.fail_notify.add(bytes(dead))
    try:
        async def run():
            await mm.fulfill(dead, MIB)
            await mm.fulfill(requester, MIB)

        loop.run_until_complete(run())
        # both negotiation records rolled back, dead's entry dropped,
        # requester re-queued
        assert len(store.get_client_negotiated_peers(requester)) == 0
        assert len(store.get_client_negotiated_peers(dead)) == 0
        assert conns.count(requester) == 0
        assert mm.pending() == 1
    finally:
        store.close()


def test_requester_push_failure_keeps_record_requeues_candidate(
        tmp_path, loop):
    store = SqliteServerStore(str(tmp_path / "s.db"))
    conns = StubConns()
    mm = _mm(store, conns)
    cand, requester = pk(1), pk(2)
    conns.fail_notify.add(bytes(requester))
    try:
        async def run():
            await mm.fulfill(cand, 2 * MIB)
            await mm.fulfill(requester, MIB)

        loop.run_until_complete(run())
        # the candidate heard about the match, so the record stays
        assert conns.count(cand) == 1
        assert len(store.get_client_negotiated_peers(requester)) == 1
        # and its unmatched remainder went back in the queue
        assert mm.pending() == 1
        entries = [e for s in mm.shards for e in s.entries.values()]
        assert entries[0][0] == cand and entries[0][1] == MIB
    finally:
        store.close()


def test_offline_entries_dropped_at_pop(tmp_path, loop):
    store = SqliteServerStore(str(tmp_path / "s.db"))
    conns = StubConns()
    mm = _mm(store, conns)
    ghost, requester = pk(1), pk(2)
    try:
        async def run():
            await mm.fulfill(ghost, MIB)
            conns.offline.add(bytes(ghost))
            await mm.fulfill(requester, MIB)

        loop.run_until_complete(run())
        assert conns.count(ghost) == 0
        assert mm.pending() == 1  # only the requester remains queued
    finally:
        store.close()
