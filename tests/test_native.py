"""Parity of the native C baseline against the CPU oracle.

``native/cdc_blake3.c`` is the honest single-thread CPU baseline the device
pipeline is benchmarked against (BASELINE.md targets); its bit-identity with
the spec implementations (`ops/cdc_cpu.py`, `ops/blake3_cpu.py`) is asserted
here over the same corpus shapes `test_backend.py` uses for the TPU path.
"""

import random

import pytest

from backuwup_tpu import native
from backuwup_tpu.ops import cdc_cpu
from backuwup_tpu.ops.blake3_cpu import blake3_hash
from backuwup_tpu.ops.gear import CDCParams

pytestmark = pytest.mark.skipif(
    not native.available(), reason="no C compiler / native lib")

PARAMS = CDCParams.from_desired(4096)


def _corpus(rng):
    return [
        b"",
        b"x",
        rng.randbytes(100),                     # < min (single runt chunk)
        rng.randbytes(PARAMS.min_size),         # exactly min
        rng.randbytes(5000),
        rng.randbytes(65536),
        rng.randbytes(65537),
        rng.randbytes(200_000),                 # multi-chunk
        b"\x00" * 50_000,                       # no candidates -> max cuts
        rng.randbytes(60_000) * 2,              # internal duplication
    ]


def test_blake3_native_parity(rng=random.Random(11)):
    for n in (0, 1, 63, 64, 65, 1023, 1024, 1025, 2048, 4097, 10_000,
              65_536, 200_001):
        data = rng.randbytes(n)
        assert native.blake3_native(data) == blake3_hash(data), n


def test_chunk_native_parity(rng=random.Random(12)):
    for data in _corpus(rng):
        assert native.chunk_native(data, PARAMS) == \
            cdc_cpu.chunk_stream(data, PARAMS), len(data)


def test_chunk_native_parity_production_params(rng=random.Random(13)):
    params = CDCParams()  # production 256 KiB / 1 MiB / 3 MiB
    data = rng.randbytes(8 << 20)
    assert native.chunk_native(data, params) == \
        cdc_cpu.chunk_stream(data, params)


def test_manifest_native_parity(rng=random.Random(14)):
    for data in _corpus(rng):
        chunks, digests = native.manifest_native(data, PARAMS)
        assert chunks == cdc_cpu.chunk_stream(data, PARAMS)
        assert digests == [blake3_hash(data[o:o + l]) for o, l in chunks]
