"""Replicated coordination metadata: op log, shipping, fencing, promote.

The protocol's commit seams get the ALICE/CrashMonkey treatment
(docs/crash_consistency.md): each registered ``repl.*`` crashpoint is
armed, the op is driven to the injected crash, and a REOPEN over the
same files must land in a clean state — either the write never became
durable anywhere (no caller was acked) or a replay/promote applies it
exactly once.  Alongside the seams: epoch fencing (a zombie primary's
stale commits refused, its divergent tail truncated when it rejoins as
a successor), gap refill after a dark successor returns, promote-time
reconciliation (the sibling with the longest acked log wins), and the
3-node permakill swarm — kill a partition owner for good and lose
nothing.

Two in-process `ReplicatedServerStore`s wired with a direct function
ship hook stand in for the HTTP pair; the swarm and the kill-9 e2e
cover the real server layer.
"""

import asyncio
import os
import subprocess
import sys
import time
from pathlib import Path

import pytest

from backuwup_tpu.net.serverstore import (OpLog, ReplicatedServerStore,
                                          ReplicationFenced,
                                          decode_value, encode_value)
from backuwup_tpu.obs import metrics as obs_metrics
from backuwup_tpu.scenario import builtin_swarms, run_swarm
from backuwup_tpu.utils import faults

pytestmark = pytest.mark.replication

PARTS = 2
MIB = 1024 * 1024
REPO = Path(__file__).resolve().parent.parent


def pk(i: int) -> bytes:
    return i.to_bytes(8, "big") + bytes(24)  # partition = i % PARTS


@pytest.fixture(autouse=True)
def _isolate():
    obs_metrics.registry().reset()
    yield
    obs_metrics.registry().reset()
    faults.uninstall()


@pytest.fixture
def plane():
    return faults.install(faults.FaultPlane(seed=7))


@pytest.fixture
def loop():
    loop = asyncio.new_event_loop()
    yield loop
    loop.close()


def _pair(root, chain=("n1",)):
    """Two (or more) wired in-process nodes: n0 owns every partition,
    ships to ``chain`` through a direct function hook."""
    stores = {"n0": ReplicatedServerStore(root / "n0", "n0", PARTS)}
    for nid in chain:
        stores[nid] = ReplicatedServerStore(root / nid, nid, PARTS)

    def ship(node, payload):
        return stores[node].accept_ship(payload)

    owners = {i: "n0" for i in range(PARTS)}
    stores["n0"].set_topology(owners=owners,
                              successors={i: list(chain)
                                          for i in range(PARTS)},
                              ship=ship)
    for nid in chain:
        stores[nid].set_topology(owners=owners)
    return stores


def _close_all(stores):
    for s in stores.values():
        s.close()


# --- the op log -------------------------------------------------------------


def test_oplog_roundtrip_tail_and_torn_tail(tmp_path):
    log = OpLog(tmp_path / "p.log")
    recs = [{"lsn": i, "epoch": 0, "op": "register_client",
             "args": encode_value([pk(i)]), "ts": 1.0} for i in (1, 2, 3)]
    log.append(recs)
    assert log.last_lsn == 3
    assert [r["lsn"] for r in log.tail(1)] == [2, 3]
    # torn tail: a crash mid-append leaves a half-written last line
    with open(tmp_path / "p.log", "ab") as fh:
        fh.write(b'{"lsn": 4, "epo')
    re1 = OpLog(tmp_path / "p.log")
    assert [r["lsn"] for r in re1.records] == [1, 2, 3]
    assert decode_value(re1.records[0]["args"]) == [pk(1)]


def test_oplog_epoch_sidecar_and_truncate(tmp_path):
    log = OpLog(tmp_path / "p.log")
    log.append([{"lsn": 1, "epoch": 0, "op": "x", "args": [], "ts": 0},
                {"lsn": 2, "epoch": 0, "op": "x", "args": [], "ts": 0}])
    log.set_epoch(3)
    log.truncate_after(1)
    re1 = OpLog(tmp_path / "p.log")
    assert re1.epoch == 3
    assert [r["lsn"] for r in re1.records] == [1]


def test_encode_decode_bytes_roundtrip():
    v = [pk(1), [pk(2), 7], "s", None, 1.5]
    assert decode_value(encode_value(v)) == v


# --- ship / ack / apply -----------------------------------------------------


def test_write_ships_to_successor_log_only(tmp_path):
    """An acked write is durable in the successor's LOG but applied to
    nothing on the successor — application waits for promote."""
    stores = _pair(tmp_path)
    try:
        stores["n0"].save_storage_negotiated(pk(0), pk(1), MIB)
        s_part = stores["n1"].parts[0]
        assert s_part.log.last_lsn == 1
        assert s_part.log.records[0]["op"] == "save_storage_negotiated"
        # not applied on the successor...
        assert stores["n1"].parts[0].get_client_negotiated_peers(pk(0)) \
            == []
        # ...but applied on the primary
        assert stores["n0"].get_client_negotiated_peers(pk(0)) == [pk(1)]
    finally:
        _close_all(stores)


def test_promote_replays_tail_exactly_once(tmp_path):
    stores = _pair(tmp_path)
    try:
        for i in (1, 3, 5):
            stores["n0"].save_storage_negotiated(pk(1), pk(i + 100), MIB)
        epoch = stores["n1"].promote(1)
        assert epoch == 1
        assert sorted(stores["n1"].parts[1]
                      .get_client_negotiated_peers(pk(1))) \
            == sorted([pk(101), pk(103), pk(105)])
        # replay again: zero records re-applied, zero rows duplicated
        assert stores["n1"].parts[1].replay() == 0
        assert len(stores["n1"].parts[1]
                   .get_client_negotiated_peers(pk(1))) == 3
    finally:
        _close_all(stores)


def test_degraded_when_chain_dark_then_gap_refill(tmp_path):
    """A dark chain degrades (availability over redundancy, counted),
    and the returning successor's gap triggers a full tail re-ship."""
    stores = _pair(tmp_path)
    down = {"flag": True}
    real_ship = stores["n0"].parts[0].ship

    def flaky(node, payload):
        if down["flag"]:
            raise ConnectionError("successor dark")
        return real_ship(node, payload)

    stores["n0"].set_topology(ship=flaky)
    try:
        from backuwup_tpu.net.serverstore import _REPL_SHIPS
        stores["n0"].save_storage_negotiated(pk(0), pk(2), MIB)  # degraded
        assert _REPL_SHIPS.value(outcome="degraded") >= 1
        assert stores["n1"].parts[0].log.last_lsn == 0
        down["flag"] = False
        stores["n0"].parts[0]._ship_down.clear()
        stores["n0"].save_storage_negotiated(pk(0), pk(4), MIB)
        # the gap (from_lsn 2 vs empty log) forced a refill from lsn 1
        assert _REPL_SHIPS.value(outcome="gap_refill") >= 1
        assert stores["n1"].parts[0].log.last_lsn == 2
    finally:
        _close_all(stores)


def test_reconciliation_sibling_with_longer_log_wins(tmp_path):
    """The dead primary acked against n1 only; promoting n2 must merge
    n1's tail before its epoch bump (the server's _promote_partition
    pull) or the acked rows die with the primary."""
    stores = _pair(tmp_path, chain=("n1", "n2"))
    # make n1 the only successor that ever saw the records
    stores["n0"].set_topology(successors={i: ["n1"]
                                          for i in range(PARTS)})
    try:
        stores["n0"].save_storage_negotiated(pk(0), pk(2), MIB)
        stores["n0"].save_storage_negotiated(pk(0), pk(4), MIB)
        assert stores["n2"].parts[0].log.last_lsn == 0
        # n0 dies; n2 reconciles from n1 then promotes
        tail = stores["n1"].log_tail(0, stores["n2"].parts[0].log.last_lsn)
        stores["n2"].accept_ship({
            "partition": 0,
            "epoch": max(tail["epoch"], stores["n2"].parts[0].log.epoch),
            "from_lsn": stores["n2"].parts[0].log.last_lsn + 1,
            "records": tail["records"]})
        stores["n2"].promote(0)
        assert sorted(stores["n2"].parts[0]
                      .get_client_negotiated_peers(pk(0))) \
            == sorted([pk(2), pk(4)])
    finally:
        _close_all(stores)


# --- fencing ----------------------------------------------------------------


def test_zombie_primary_fenced_and_divergent_tail_truncated(tmp_path):
    """The fencing gate: after a successor promotes, the old primary's
    commits are refused (its write futures fail ReplicationFenced), its
    unacked divergent tail is truncated when the new primary ships to
    it, and no row is ever double-applied."""
    stores = _pair(tmp_path)
    try:
        stores["n0"].save_storage_negotiated(pk(0), pk(2), MIB)
        # network partitions: n0 keeps running but its ships vanish
        stores["n0"].set_topology(
            ship=lambda node, payload: (_ for _ in ()).throw(
                ConnectionError("partitioned")))
        stores["n0"].save_storage_negotiated(pk(0), pk(4), MIB)  # degraded
        assert stores["n0"].parts[0].log.last_lsn == 2  # divergent tail
        # the successor promotes past it
        assert stores["n1"].promote(0) == 1
        # heal the partition: n0's next commit is fenced, nothing applies
        def ship_back(node, payload):
            return stores[node].accept_ship(payload)
        stores["n0"].set_topology(ship=ship_back)
        with pytest.raises(ReplicationFenced) as ei:
            stores["n0"].save_storage_negotiated(pk(0), pk(6), MIB)
        assert ei.value.epoch == 1
        assert stores["n0"].parts[0].fenced
        # ...and stays fenced without any ship round-trip
        with pytest.raises(ReplicationFenced):
            stores["n0"].register_client(pk(0))
        # n0 rejoins as successor: the new primary's first ship carries
        # the higher epoch, truncating n0's divergent unacked tail
        stores["n1"].set_topology(
            owners={i: "n1" for i in range(PARTS)},
            successors={i: ["n0"] for i in range(PARTS)}, ship=ship_back)
        stores["n1"].save_storage_negotiated(pk(0), pk(8), MIB)
        n0_part = stores["n0"].parts[0]
        assert [r["lsn"] for r in n0_part.log.records] == [1, 2]
        assert n0_part.log.records[-1]["epoch"] == 1
        assert decode_value(n0_part.log.records[-1]["args"])[1] == pk(8)
        assert n0_part.log.epoch == 1
        assert not n0_part.fenced
        # the truncation forced a rebuild: the zombie's divergent pk(4)
        # row (applied locally in degraded mode) is gone from sqlite,
        # and the rebuilt state is exactly the surviving log
        assert not n0_part.log.dirty
        assert sorted(n0_part.get_client_negotiated_peers(pk(0))) \
            == sorted([pk(2), pk(8)])
        # no double-applied rows: promote n0 and diff
        stores["n0"].promote(0)
        assert sorted(n0_part.get_client_negotiated_peers(pk(0))) \
            == sorted([pk(2), pk(8)])
    finally:
        _close_all(stores)


def test_stale_epoch_ship_refused_at_intake(tmp_path):
    stores = _pair(tmp_path)
    try:
        stores["n1"].promote(0)
        resp = stores["n1"].accept_ship({
            "partition": 0, "epoch": 0, "from_lsn": 1,
            "records": [{"lsn": 1, "epoch": 0, "op": "register_client",
                         "args": encode_value([pk(0)]), "ts": 1.0}]})
        assert resp["fenced"] and resp["epoch"] == 1
        assert stores["n1"].parts[0].log.last_lsn == 0
    finally:
        _close_all(stores)


# --- the crash seams: arm -> crash -> reopen clean --------------------------


def _reopen(root, nid="n0"):
    return ReplicatedServerStore(root / nid, nid, PARTS)


def test_seam_append_pre_crash_leaves_no_trace(tmp_path, plane):
    stores = _pair(tmp_path)
    plane.arm_crash("repl.log.append.pre")
    with pytest.raises(faults.CrashInjected):
        stores["n0"].register_client(pk(0))
    re0 = _reopen(tmp_path)
    try:
        assert re0.parts[0].log.last_lsn == 0
        assert not re0.client_exists(pk(0))
        assert stores["n1"].parts[0].log.last_lsn == 0
    finally:
        re0.close()
        _close_all(stores)


def test_seam_append_post_crash_record_durable_not_applied(tmp_path, plane):
    """Crash between the log fsync and the ship: the record is durable
    on the primary only, the caller was NEVER acked, and a reopen does
    not silently apply it — promote does, exactly once."""
    stores = _pair(tmp_path)
    plane.arm_crash("repl.log.append.post")
    with pytest.raises(faults.CrashInjected):
        stores["n0"].register_client(pk(0))
    re0 = _reopen(tmp_path)
    try:
        assert re0.parts[0].log.last_lsn == 1
        assert not re0.client_exists(pk(0))  # reopen never auto-applies
        assert re0.promote(0) == 1
        assert re0.client_exists(pk(0))
        assert re0.parts[0].replay() == 0  # exactly once
    finally:
        re0.close()
        _close_all(stores)


def test_seam_ship_acked_crash_rolls_forward_on_next_batch(tmp_path, plane):
    """Crash after the successor ack, before the sqlite apply: the
    record out-survives the primary (successor log has it) AND the
    reopened primary's next write batch rolls the unapplied tail
    forward in the same transaction."""
    stores = _pair(tmp_path)
    plane.arm_crash("repl.ship.acked")
    with pytest.raises(faults.CrashInjected):
        stores["n0"].save_storage_negotiated(pk(0), pk(2), MIB)
    assert stores["n1"].parts[0].log.last_lsn == 1  # acked pre-crash
    re0 = _reopen(tmp_path)
    try:
        assert not re0.get_client_negotiated_peers(pk(0))
        re0.save_storage_negotiated(pk(0), pk(4), MIB)
        assert sorted(re0.get_client_negotiated_peers(pk(0))) \
            == sorted([pk(2), pk(4)])
        assert re0.parts[0].applied_lsn() == 2
    finally:
        re0.close()
        _close_all(stores)


def test_seam_promote_pre_crash_is_retryable(tmp_path, plane):
    stores = _pair(tmp_path)
    stores["n0"].register_client(pk(0))
    plane.arm_crash("repl.promote.pre")
    with pytest.raises(faults.CrashInjected):
        stores["n1"].promote(0)
    assert stores["n1"].parts[0].log.epoch == 0  # bump never committed
    re1 = _reopen(tmp_path, "n1")
    try:
        assert re1.promote(0) == 1
        assert re1.client_exists(pk(0))
    finally:
        re1.close()
        _close_all(stores)


def test_seam_promote_post_crash_replay_already_applied(tmp_path, plane):
    """Crash after the epoch bump + replay: the reopened node re-runs
    promote; the second replay applies zero records and rows stay
    exactly-once (epochs only need monotonicity, so the extra bump is
    harmless)."""
    stores = _pair(tmp_path)
    stores["n0"].save_storage_negotiated(pk(0), pk(2), MIB)
    plane.arm_crash("repl.promote.post")
    with pytest.raises(faults.CrashInjected):
        stores["n1"].promote(0)
    re1 = _reopen(tmp_path, "n1")
    try:
        assert re1.parts[0].log.epoch == 1
        assert re1.parts[0].replay() == 0  # crash hit AFTER the replay
        assert re1.promote(0) == 2
        assert re1.parts[0].get_client_negotiated_peers(pk(0)) == [pk(2)]
    finally:
        re1.close()
        _close_all(stores)


def test_seam_successor_intake_crash_keeps_log_loadable(tmp_path, plane):
    stores = _pair(tmp_path)
    payload = {"partition": 0, "epoch": 0, "from_lsn": 1,
               "records": [{"lsn": 1, "epoch": 0, "op": "register_client",
                            "args": encode_value([pk(0)]), "ts": 1.0}]}
    plane.arm_crash("repl.log.append.pre")
    with pytest.raises(faults.CrashInjected):
        stores["n1"].accept_ship(payload)
    re1 = _reopen(tmp_path, "n1")
    try:
        assert re1.parts[0].log.last_lsn == 0
        # retry after "restart" lands cleanly
        assert re1.accept_ship(payload)["acked"]
        assert re1.parts[0].log.last_lsn == 1
    finally:
        re1.close()
        _close_all(stores)


# --- the permakill swarm ----------------------------------------------------


@pytest.mark.swarm
@pytest.mark.timeout(240)
def test_replication_swarm_permakill(tmp_path, loop):
    """Tier-1 replication acceptance: 3 nodes, per-node replicated
    stores, a partition-owning node killed for good mid-run.  Gates:
    a successor promoted within the probe deadline, matchmaking flow
    continued after the promotion, and zero durable matchmaking rows
    lost even though the only node that ever APPLIED those partitions'
    writes is gone."""
    spec = builtin_swarms()["replication"]
    card, summary = loop.run_until_complete(run_swarm(spec, tmp_path))
    assert card.passed, card.render()
    gates = {a.name: a.passed for a in card.assertions}
    for gate in ("federation_no_lost_matchmakings",
                 "replication_promoted",
                 "replication_post_promote_flow",
                 "replication_durability_invariant",
                 "federation_p99_bounded",
                 "commits_off_event_loop"):
        assert gates.get(gate) is True, (gate, card.render())
    assert summary["nodes"] == 3
    assert summary["shared_store"] is False
    assert summary["permakills"] == 1
    assert summary["promotions"] >= 1
    assert summary["repl_promote_s"] is not None
    assert summary["post_promote_matchmakings"] > 0
    assert summary["negotiated_rows"] >= 2 * summary["total_matchmakings"]


@pytest.mark.swarm
@pytest.mark.slow
@pytest.mark.timeout(600)
def test_replication_swarm_soak(tmp_path, loop):
    spec = builtin_swarms()["replication_soak"]
    card, summary = loop.run_until_complete(run_swarm(spec, tmp_path))
    assert card.passed, card.render()
    assert summary["permakills"] == 1
    assert summary["negotiated_rows"] >= 2 * summary["total_matchmakings"]


# --- kill-9 e2e on the promote path -----------------------------------------

_CHILD = """
import sys
from backuwup_tpu.utils import faults
faults.install(faults.from_env())
from backuwup_tpu.net.serverstore import ReplicatedServerStore
s = ReplicatedServerStore(sys.argv[1], node_id="n1", partitions=2)
s.promote(0)
print("promoted-clean")  # unreachable when the crash is armed
"""


@pytest.mark.slow
@pytest.mark.timeout(120)
def test_kill9_during_promote_then_clean_promotion(tmp_path):
    """A real successor process hard-exits (os._exit(70)) mid-promote;
    the restarted node promotes cleanly and every acked record is
    applied exactly once."""
    # build the successor state: two acked records in the log, nothing
    # applied (the parent plays the dead primary shipping a tail)
    seed = ReplicatedServerStore(tmp_path / "n1", "n1", PARTS)
    resp = seed.accept_ship({
        "partition": 0, "epoch": 0, "from_lsn": 1,
        "records": [
            {"lsn": 1, "epoch": 0, "op": "register_client",
             "args": encode_value([pk(0)]), "ts": 1.0},
            {"lsn": 2, "epoch": 0, "op": "save_storage_negotiated",
             "args": encode_value([pk(0), pk(2), MIB]), "ts": 2.0}]})
    assert resp["acked"]
    seed.close()
    env = dict(os.environ,
               BKW_FAULTS="crash=repl.promote.post@0,crash_hard=1",
               PYTHONPATH=str(REPO), JAX_PLATFORMS="cpu")
    proc = subprocess.Popen(
        [sys.executable, "-c", _CHILD, str(tmp_path / "n1")],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE)
    out, err = proc.communicate(timeout=90)
    assert proc.returncode == faults.CRASH_EXIT_CODE, (out, err)
    assert b"promoted-clean" not in out
    # restart: promote again, rows exactly once
    node = ReplicatedServerStore(tmp_path / "n1", "n1", PARTS)
    try:
        assert node.parts[0].log.epoch == 1  # the bump survived
        assert node.parts[0].replay() == 0  # replay ran before the kill
        epoch = node.promote(0)
        assert epoch == 2
        assert node.client_exists(pk(0))
        assert node.get_client_negotiated_peers(pk(0)) == [pk(2)]
    finally:
        node.close()
