"""P2P data plane over loopback: rendezvous, signed transfer, restore-back."""

import asyncio

import pytest

from backuwup_tpu import defaults, wire
from backuwup_tpu.crypto import KeyManager
from backuwup_tpu.net.client import ServerClient
from backuwup_tpu.net.p2p import (
    P2PError,
    P2PNode,
    ReceivedFilesWriter,
    RestoreFilesWriter,
    obfuscate,
)
from backuwup_tpu.net.server import CoordinationServer
from backuwup_tpu.store import Store


def test_obfuscation_round_trip(rng):
    data = rng.randbytes(123_123)
    key = b"\xaa\x01\x7f\x33"
    assert obfuscate(obfuscate(data, key), key) == data
    assert obfuscate(data, key) != data


@pytest.fixture
def loop():
    loop = asyncio.new_event_loop()
    yield loop
    loop.close()


async def _make_node(tmp_path, name, port, monkeypatch_data_dir):
    keys = KeyManager.from_secret(bytes([len(name)]) * 31 + name.encode()[:1])
    store = Store(tmp_path / name / "cfg")
    store.set_obfuscation_key(b"\x11\x22\x33\x44")
    client = ServerClient(keys, store, addr=f"127.0.0.1:{port}")
    await client.register()
    await client.login()
    node = P2PNode(keys, store, client)
    client.start_ws()
    await asyncio.wait_for(client.ws_connected.wait(), 5)
    return keys, store, client, node


def test_transfer_and_restore_cycle(tmp_path, loop, monkeypatch):
    """A stores two packfiles + an index on B, then restores them back."""
    monkeypatch.setenv("DATA_DIR", str(tmp_path / "b" / "data"))

    async def run():
        server = CoordinationServer()
        port = await server.start()
        ka, sa, ca, na = await _make_node(tmp_path, "a", port, None)
        kb, sb, cb, nb = await _make_node(tmp_path, "b", port, None)

        # peers know each other via a negotiated match (ledger rows)
        sa.add_peer_negotiated(kb.client_id, 10_000_000)
        sb.add_peer_negotiated(ka.client_id, 10_000_000)

        received_done = asyncio.Event()

        async def on_transport(source, transport):
            from backuwup_tpu.net.p2p import Receiver
            writer = ReceivedFilesWriter(sb, source)
            await Receiver(transport, writer.sink).run()
            received_done.set()

        nb.on_transport_request = on_transport
        nb.on_restore_request = lambda src, t: nb.serve_restore(src, t)

        async def on_restore(source, transport):
            await nb.serve_restore(source, transport)

        nb.on_restore_request = on_restore

        # --- A -> B transfer ------------------------------------------------
        t = await na.connect(kb.client_id, wire.RequestType.TRANSPORT)
        pid1, pid2 = b"\x01" * 12, b"\x02" * 12
        data1, data2 = b"packfile-one" * 1000, b"packfile-two" * 2000
        index0 = b"index-file-zero" * 100
        await t.send_data(data1, wire.FileInfoKind.PACKFILE, pid1)
        await t.send_data(data2, wire.FileInfoKind.PACKFILE, pid2)
        await t.send_data(index0, wire.FileInfoKind.INDEX,
                          (0).to_bytes(8, "little"))
        await t.close()
        await asyncio.wait_for(received_done.wait(), 10)

        # stored obfuscated, accounted, de-obfuscatable
        peer = sb.get_peer(ka.client_id)
        assert peer.bytes_received == len(data1) + len(data2) + len(index0)
        stored = list(ReceivedFilesWriter(sb, ka.client_id).iter_stored())
        assert {s[1]: s[2] for s in stored if s[0] == wire.FileInfoKind.PACKFILE} \
            == {pid1: data1, pid2: data2}
        raw_on_disk = next(
            (sb.received_dir(ka.client_id) / "pack" / pid1.hex()).parent.glob(
                pid1.hex())).read_bytes()
        assert raw_on_disk != data1  # obfuscated at rest

        # --- A <- B restore -------------------------------------------------
        restorer = RestoreFilesWriter(sa)
        tr = await na.connect(kb.client_id, wire.RequestType.RESTORE_ALL)
        from backuwup_tpu.net.p2p import Receiver
        got = await Receiver(tr, restorer.sink).run()
        assert got == 3
        pack_dir = sa.restore_dir() / "pack" / pid1.hex()[:2]
        assert (pack_dir / pid1.hex()).read_bytes() == data1

        # immediate second restore is throttled (60 s rate limit)
        tr2 = await na.connect(kb.client_id, wire.RequestType.RESTORE_ALL)
        got2 = await Receiver(tr2, restorer.sink).run()
        assert got2 == 0  # serve_restore raised before sending anything

        await ca.close()
        await cb.close()
        await server.stop()

    loop.run_until_complete(asyncio.wait_for(run(), 60))


def test_unknown_peer_connection_refused(tmp_path, loop, monkeypatch):
    """B ignores rendezvous from clients not in its peer ledger."""
    monkeypatch.setenv("DATA_DIR", str(tmp_path / "bx" / "data"))

    async def run():
        server = CoordinationServer()
        port = await server.start()
        ka, sa, ca, na = await _make_node(tmp_path, "ax", port, None)
        kb, sb, cb, nb = await _make_node(tmp_path, "bx", port, None)
        # no ledger rows: B refuses to even confirm
        with pytest.raises(P2PError):
            await na.connect(kb.client_id, wire.RequestType.TRANSPORT,
                             timeout=1.5)
        await ca.close()
        await cb.close()
        await server.stop()

    loop.run_until_complete(asyncio.wait_for(run(), 30))


def test_quota_enforced(tmp_path, loop, monkeypatch):
    monkeypatch.setenv("DATA_DIR", str(tmp_path / "bq" / "data"))
    # shrink the overuse grace so a transport-sized file can exceed quota
    monkeypatch.setattr(defaults, "PEER_OVERUSE_GRACE", 1024)

    async def run():
        server = CoordinationServer()
        port = await server.start()
        ka, sa, ca, na = await _make_node(tmp_path, "aq", port, None)
        kb, sb, cb, nb = await _make_node(tmp_path, "bq", port, None)
        sa.add_peer_negotiated(kb.client_id, 100)
        sb.add_peer_negotiated(ka.client_id, 100)  # tiny quota

        failures = []

        async def on_transport(source, transport):
            from backuwup_tpu.net.p2p import Receiver
            writer = ReceivedFilesWriter(sb, source)
            try:
                await Receiver(transport, writer.sink).run()
            except P2PError as e:
                failures.append(e)

        nb.on_transport_request = on_transport
        t = await na.connect(kb.client_id, wire.RequestType.TRANSPORT)
        big = b"\x00" * (defaults.PEER_OVERUSE_GRACE + 1000 + 100)
        with pytest.raises(P2PError):  # no ack comes back
            await t.send_data(big, wire.FileInfoKind.PACKFILE, b"\x03" * 12)
        await t.close()
        await asyncio.sleep(0.2)
        assert failures, "receiver must reject over-quota file"
        await ca.close()
        await cb.close()
        await server.stop()

    loop.run_until_complete(asyncio.wait_for(run(), 30))
