"""v2 (packed-u32) fused-scan kernel logic vs the XLA oracle.

The Mosaic lowering itself can only be proven on TPU (the import-time
parity ladder in ``scan_fused.fused_scan_available`` does that on the
live runtime); here the kernel BODY runs in pallas interpret mode on
CPU, which validates the plane-permutation ladder, halo plumbing, and
bit-pack math that v2 reimplements.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from conftest import pallas_interpret_works
from backuwup_tpu.ops import scan_fused
from backuwup_tpu.ops.cdc_tpu import _candidate_words, _hash_ext_fast

if not pallas_interpret_works():  # pragma: no cover
    pytest.skip("pallas interpret mode unavailable on this host",
                allow_module_level=True)


@pytest.mark.parametrize("case", ["random", "zeros", "short_rows",
                                  "multi_tile", "min_p", "single_row"])
def test_v2_kernel_matches_xla_oracle(case):
    rng = np.random.default_rng(42)
    # multi_tile: S32 = P/512 = 2048 > R32 = 512 -> 4 grid steps, so the
    # prev-tile halo branch (i > 0) is exercised, not just halo0;
    # min_p: P=4096 makes R32 == HR == 8 (tightest legal geometry)
    P = {"multi_tile": 1 << 20, "min_p": 4096}.get(case, 64 * 1024)
    B = 1 if case == "single_row" else 2
    ext = rng.integers(0, 256, (B, 31 + P), dtype=np.uint8)
    if case == "zeros":
        ext[0] = 0
    nv = np.full(B, P, dtype=np.int32)
    if case == "short_rows":
        nv[1] = P - 12345
    mask_s, mask_l = 0xFFF00000, 0xFFF80000
    wl, ws = scan_fused._fused_candidate_words_u32(
        jnp.asarray(ext), jnp.asarray(nv),
        mask_s=mask_s, mask_l=mask_l, interpret=True)
    for r in range(B):
        h = _hash_ext_fast(jnp.asarray(ext[r]))
        rl, rs = _candidate_words(h, jnp.int32(nv[r]),
                                  jnp.uint32(mask_s), jnp.uint32(mask_l))
        assert np.array_equal(np.asarray(wl[r]), np.asarray(rl)), case
        assert np.array_equal(np.asarray(ws[r]), np.asarray(rs)), case
