"""Swarm restore data plane (PR 11): k-of-n multi-source pulls.

Unit coverage of the planner (k fastest holders become primaries under
the peer-stats estimators), the scheduler's download lanes (hedged
pulls, stalled-transfer re-queue onto a different peer), plus loopback
e2e proofs: a dark holder mid-restore costs nothing, a slow holder is
hedged around, and a peer speaking only the legacy RESTORE_ALL protocol
still restores byte-for-byte through the fallback path.
"""

import asyncio
import time
from types import SimpleNamespace

import pytest

from backuwup_tpu import defaults
from backuwup_tpu.crypto import KeyManager
from backuwup_tpu.engine import Engine
from backuwup_tpu.net.p2p import P2PError, RestoreFilesWriter
from backuwup_tpu.net.peer_stats import PeerEstimate
from backuwup_tpu.net.transfer import TransferScheduler
from backuwup_tpu.obs import journal as obs_journal
from backuwup_tpu.obs import metrics as obs_metrics
from backuwup_tpu.ops.backend import CpuBackend
from backuwup_tpu.ops.gear import CDCParams
from backuwup_tpu.scenario import Phase, ScenarioHarness, ScenarioSpec
from backuwup_tpu.store import Store

pytestmark = pytest.mark.concurrency


def _run(coro, timeout=120):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(asyncio.wait_for(coro, timeout))
    finally:
        loop.close()


def _fam_total(name: str, **labels) -> float:
    fam = obs_metrics.registry().snapshot().get(name) or {}
    total = 0.0
    for s in fam.get("series", []):
        if all(s.get("labels", {}).get(k) == v for k, v in labels.items()):
            total += s["value"]
    return total


@pytest.fixture
def engine(tmp_path):
    keys = KeyManager.generate()
    store = Store(directory=tmp_path / "cfg", data_base=tmp_path / "data")
    eng = Engine(keys, store, server=None, node=None,
                 backend=CpuBackend(CDCParams.from_desired(4096)))
    yield eng
    store.close()


def _seed_estimate(eng, peer: bytes, bps: float, samples: int = 10):
    with eng.peer_stats._lock:
        eng.peer_stats._est[bytes(peer)] = PeerEstimate(
            peer=bytes(peer), throughput_bps=bps, latency_s=0.01,
            success=1.0, samples=samples, updated=time.time())


# --- planner: source selection ----------------------------------------------

def test_planner_pulls_from_the_k_fastest_holders(engine):
    """6 holders with seeded estimator rates: only the RS_K fastest are
    submitted as primaries; the slow tail stays in reserve as spares."""
    pid = b"\x61" * 12
    holders = [bytes([0x70 + i]) * 32 for i in range(6)]
    # ranks: holder i measures (i+1)*1e6 B/s -> fastest are the last 4
    for i, h in enumerate(holders):
        _seed_estimate(engine, h, (i + 1) * 1e6)
    shard_map = {i: (h, 4096) for i, h in enumerate(holders)}
    writer = RestoreFilesWriter(engine.store)

    class FakeSched:
        def __init__(self):
            self.submitted = []

        def submit_pull(self, peer, size, job, label=""):
            self.submitted.append(bytes(peer))

            async def done():
                return SimpleNamespace(ok=True, peer_id=bytes(peer))
            return asyncio.ensure_future(done())

        async def pull_hedged(self, primary, spawn_hedge, hedge_after_s):
            return await primary

    async def go():
        sched = FakeSched()
        got = await engine._pull_stripe(pid, shard_map, writer, sched)
        return sched, got

    sched, got = _run(go())
    assert got == defaults.RS_K
    assert len(sched.submitted) == defaults.RS_K
    # exactly the 4 fastest (holders 2..5), none of the slow tail
    assert set(sched.submitted) == set(holders[-defaults.RS_K:])


def test_unmeasured_holder_scores_neutral(engine):
    """Below PLACEMENT_MIN_SAMPLES the estimator says nothing: the rate
    is the neutral placement score, not zero — a cold holder is neither
    first pick nor untouchable."""
    cold, slow = b"\x01" * 32, b"\x02" * 32
    _seed_estimate(engine, cold, 99e6, samples=1)  # too few samples
    _seed_estimate(engine, slow, 1e3)
    assert engine._pull_rate(cold) == float(
        defaults.PLACEMENT_NEUTRAL_SCORE_BPS)
    assert engine._pull_rate(slow) < engine._pull_rate(cold)


# --- scheduler: hedged pulls and re-queue ------------------------------------

def test_hedge_fires_on_stall_and_redundant_shard_wins():
    """A primary pull stalled past the hedge deadline races a spare; the
    spare delivers and the outcome counts as won."""
    won0 = _fam_total("bkw_restore_hedges_total", outcome="won")

    async def go():
        sched = TransferScheduler()
        stalled, hedged = b"\x0a" * 32, b"\x0b" * 32

        async def stall():
            await asyncio.sleep(30)
            return 10

        async def quick():
            return 10

        primary = sched.submit_pull(stalled, 10, stall, label="r:p")

        def spawn_hedge():
            return sched.submit_pull(hedged, 10, quick, label="r:h")

        res = await sched.pull_hedged(primary, spawn_hedge, 0.05)
        return res, hedged

    res, hedged = _run(go())
    assert res is not None and res.ok
    assert bytes(res.peer_id) == hedged
    assert _fam_total("bkw_restore_hedges_total", outcome="won") == won0 + 1


def test_primary_recovery_counts_hedge_as_lost():
    """The hedge launches but the lagging primary finishes first: its
    result is used and the hedge is accounted lost, not won."""
    lost0 = _fam_total("bkw_restore_hedges_total", outcome="lost")

    async def go():
        sched = TransferScheduler()
        lagging, spare = b"\x0c" * 32, b"\x0d" * 32

        async def lag():
            await asyncio.sleep(0.2)
            return 10

        async def very_slow():
            await asyncio.sleep(30)
            return 10

        primary = sched.submit_pull(lagging, 10, lag, label="r:p")

        def spawn_hedge():
            return sched.submit_pull(spare, 10, very_slow, label="r:h")

        res = await sched.pull_hedged(primary, spawn_hedge, 0.05)
        return res, lagging

    res, lagging = _run(go())
    assert res is not None and res.ok
    assert bytes(res.peer_id) == lagging
    assert _fam_total("bkw_restore_hedges_total",
                      outcome="lost") == lost0 + 1


def test_requeued_download_lands_on_a_different_peer():
    """A failed pull re-queues behind the next-ranked source instead of
    hammering the same peer."""

    async def go():
        sched = TransferScheduler()
        bad, good = b"\x0e" * 32, b"\x0f" * 32
        attempts = []

        def make_pull(peer):
            async def job():
                attempts.append(bytes(peer))
                if bytes(peer) == bad:
                    raise P2PError("injected stall")
                return 7
            return job

        res = await sched.pull_with_requeue([bad, good], 7, make_pull,
                                            label="r:q")
        return res, attempts, bad, good

    res, attempts, bad, good = _run(go())
    assert res is not None and res.ok
    assert bytes(res.peer_id) == good
    assert attempts == [bad, good]
    # the winning result carries no residue of the failed first attempt
    assert res.error is None


# --- loopback e2e ------------------------------------------------------------

@pytest.fixture(autouse=True)
def _isolate():
    """Registry + journal isolation, same posture as test_scenario.py."""
    obs_metrics.registry().reset()
    yield
    obs_metrics.registry().reset()
    obs_journal.uninstall()


@pytest.fixture
def loop():
    loop = asyncio.new_event_loop()
    yield loop
    loop.close()


def _striped_holders(harness):
    return sorted({peer for _, peer, _s, idx, _ in
                   harness.a.store.all_placements() if idx >= 0})


def test_dark_holder_mid_restore_costs_nothing(tmp_path, loop):
    """A holder that goes permanently dark between backup and restore
    contributes zero pulled bytes; the spares cover its stripes and the
    restore still verifies byte-for-byte."""
    spec = ScenarioSpec(name="dark", seed=7,
                        phases=(Phase("backup"), Phase("restore")))

    async def run():
        h = ScenarioHarness(spec, tmp_path)
        await h.setup()
        try:
            await h._phase_backup(Phase("backup"))
            victim = _striped_holders(h)[0]
            h.plane.kill(victim)
            await h._phase_restore(Phase("restore"))
            assert h.facts["restore_verified"] is True
            label = bytes(victim).hex()[:16]
            assert _fam_total("bkw_restore_bytes_pulled_total",
                              peer=label) == 0
            assert _fam_total("bkw_restore_bytes_pulled_total") > 0
        finally:
            await h.teardown()

    loop.run_until_complete(run())


def test_slow_and_dark_holder_e2e_restores_byte_for_byte(tmp_path, loop):
    """The acceptance composition: one measured-fast holder stalls every
    frame (hedged around, outcome won) while another is dark (re-queued
    around), and the restore still verifies byte-for-byte."""
    spec = ScenarioSpec(name="slowdark", seed=17, spares=2,
                        phases=(Phase("backup"), Phase("restore_hedged")))

    async def run():
        h = ScenarioHarness(spec, tmp_path)
        await h.setup()
        try:
            await h._phase_backup(Phase("backup"))
            placed = _striped_holders(h)
            dark = placed[1]  # the hedged phase stalls placed[0]
            h.plane.kill(dark)
            await h._phase_restore_hedged(Phase("restore_hedged"))
            assert h.facts["restore_verified"] is True
            assert _fam_total("bkw_restore_hedges_total",
                              outcome="won") >= 1
            assert _fam_total("bkw_restore_bytes_pulled_total",
                              peer=bytes(dark).hex()[:16]) == 0
        finally:
            await h.teardown()

    loop.run_until_complete(run())


def test_legacy_restore_all_only_peers_still_restore(tmp_path, loop):
    """Interop: holders that predate the shard-granular fetch protocol
    (RESTORE_FETCH falls on deaf ears) force the coverage-gap fallback
    to full RESTORE_ALL streams — the restore completes byte-for-byte
    through the legacy path."""
    spec = ScenarioSpec(name="legacy", seed=27,
                        phases=(Phase("backup"), Phase("restore")))

    async def run():
        h = ScenarioHarness(spec, tmp_path)
        await h.setup()
        try:
            await h._phase_backup(Phase("backup"))
            for holder in h.holders + h.spares:
                # an old peer accepts the dial but has no fetch handler
                holder.node.on_restore_fetch_request = None
            await h._phase_restore(Phase("restore"))
            assert h.facts["restore_verified"] is True
        finally:
            await h.teardown()

    loop.run_until_complete(run())
