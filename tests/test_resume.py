"""Resumable WAN transfer plane (docs/transfer.md resume protocol).

Units for the pieces — PartialStore contiguity/verification,
validate_resume_offer outcomes, adaptive deadlines, the outbound fault
chokepoint, sequence-break telemetry, capacity-aware placement — plus
loopback e2e runs proving a chunked transfer survives an injected
mid-transfer cut by resuming from the receiver's verified partial
(re-sent bytes a fraction of the payload, never the whole file again).
"""

import asyncio
import time
from dataclasses import dataclass

import pytest

from backuwup_tpu import defaults, wire
from backuwup_tpu.crypto import KeyManager
from backuwup_tpu.net.client import ServerClient
from backuwup_tpu.net.p2p import (
    P2PError,
    P2PNode,
    PartialStore,
    ReceivedFilesWriter,
    Receiver,
    SendProgress,
    Transport,
    adaptive_deadline,
    validate_resume_offer,
)
from backuwup_tpu.net.peer_stats import PeerStats
from backuwup_tpu.net.server import CoordinationServer
from backuwup_tpu.obs import metrics as obs_metrics
from backuwup_tpu.ops.blake3_cpu import blake3_many
from backuwup_tpu.store import PeerStatsRow, Store
from backuwup_tpu.utils import faults

K = wire.FileInfoKind.PACKFILE
NONCE = b"\x00" * 16


def _digest(data: bytes) -> bytes:
    return blake3_many([data])[0]


def _fam_total(name: str, **labels) -> float:
    """Sum a counter family's series, optionally filtered by labels."""
    fam = obs_metrics.registry().snapshot().get(name) or {}
    total = 0.0
    for s in fam.get("series", []):
        if all(s.get("labels", {}).get(k) == v for k, v in labels.items()):
            total += s["value"]
    return total


@pytest.fixture
def loop():
    loop = asyncio.new_event_loop()
    yield loop
    loop.close()


# --- adaptive deadlines -----------------------------------------------------


def test_adaptive_deadline_scales_with_size_and_caps():
    base = defaults.ACK_TIMEOUT_S
    floor = defaults.TRANSFER_MIN_THROUGHPUT_BPS
    assert adaptive_deadline(0) == pytest.approx(base)
    assert adaptive_deadline(floor) == pytest.approx(base + 1.0)
    # a fast measured peer tightens the budget below the min-rate floor's
    hundred_mib = 100 << 20
    assert adaptive_deadline(hundred_mib, 100e6) \
        < adaptive_deadline(hundred_mib)
    # but never below the ack floor, and never above the cap
    assert adaptive_deadline(1, 1e12) >= base
    assert adaptive_deadline(1 << 40) == defaults.TRANSFER_DEADLINE_CAP_S


# --- PartialStore -----------------------------------------------------------


def test_partial_store_contiguous_roundtrip(tmp_path, rng):
    ps = PartialStore(tmp_path / "partial")
    data = rng.randbytes(10_240)
    dg, fid = _digest(data), b"\x01" * 12
    assert ps.append(K, fid, 0, len(data), dg, data[:4096]) is None
    held, digest, prefix = ps.query(fid)
    assert (held, digest, prefix) == (4096, dg, _digest(data[:4096]))
    assert ps.append(K, fid, 4096, len(data), dg, data[4096:8192]) is None
    assert ps.append(K, fid, 8192, len(data), dg, data[8192:]) == data
    # completion consumes the staging files
    assert ps.query(fid) == (0, b"", b"")


def test_partial_store_rejects_gaps_and_unknown_continuations(tmp_path, rng):
    ps = PartialStore(tmp_path / "partial")
    data = rng.randbytes(12_288)
    dg = _digest(data)
    with pytest.raises(P2PError, match="unknown partial"):
        ps.append(K, b"\x02" * 12, 4096, len(data), dg, data[4096:8192])
    ps.append(K, b"\x03" * 12, 0, len(data), dg, data[:4096])
    with pytest.raises(P2PError, match="non-contiguous"):
        ps.append(K, b"\x03" * 12, 8192, len(data), dg, data[8192:])


def test_partial_store_metadata_mismatch_discards(tmp_path, rng):
    ps = PartialStore(tmp_path / "partial")
    data, fid = rng.randbytes(12_288), b"\x04" * 12
    ps.append(K, fid, 0, len(data), _digest(data), data[:4096])
    # a continuation claiming a different file version kills the partial
    with pytest.raises(P2PError, match="metadata mismatch"):
        ps.append(K, fid, 4096, len(data), _digest(b"other"),
                  data[4096:8192])
    assert ps.query(fid) == (0, b"", b"")


def test_partial_store_part_zero_truncates_stale_bytes(tmp_path, rng):
    """A sender restarting from zero (stale/corrupt offer) implicitly
    discards whatever the receiver held for that file id."""
    ps = PartialStore(tmp_path / "partial")
    fid = b"\x05" * 12
    old, new = rng.randbytes(10_240), rng.randbytes(8192)
    ps.append(K, fid, 0, len(old), _digest(old), old[:4096])
    ps.append(K, fid, 0, len(new), _digest(new), new[:4096])
    held, digest, prefix = ps.query(fid)
    assert (held, digest, prefix) == (4096, _digest(new),
                                      _digest(new[:4096]))
    assert ps.append(K, fid, 4096, len(new), _digest(new), new[4096:]) == new


def test_partial_store_assembled_digest_mismatch_discards(tmp_path, rng):
    """A corrupted partial is discarded and never handed to the sink."""
    ps = PartialStore(tmp_path / "partial")
    data, fid = rng.randbytes(8192), b"\x06" * 12
    wrong = _digest(b"not-this-file")
    ps.append(K, fid, 0, len(data), wrong, data[:4096])
    with pytest.raises(P2PError, match="digest mismatch"):
        ps.append(K, fid, 4096, len(data), wrong, data[4096:])
    assert ps.query(fid) == (0, b"", b"")


# --- RESUME_OFFER validation ------------------------------------------------


def _offer(fid: bytes, offset: int, digest: bytes,
           prefix: bytes) -> wire.P2PBody:
    return wire.P2PBody(
        kind=wire.P2PBodyKind.RESUME_OFFER,
        header=wire.P2PHeader(sequence_number=1, session_nonce=NONCE),
        file_id=fid, offset=offset, file_digest=digest,
        prefix_digest=prefix)


def test_resume_offer_verified_prefix_resumes(rng):
    data, fid = rng.randbytes(10_000), b"\x11" * 12
    dg = _digest(data)
    offer = _offer(fid, 4096, dg, _digest(data[:4096]))
    assert validate_resume_offer(offer, data, dg, fid) == (4096, "resumed")


def test_resume_offer_stale_digest_restarts_clean(rng):
    """The receiver holds a partial of an older file version: restart."""
    data, fid = rng.randbytes(10_000), b"\x12" * 12
    old = rng.randbytes(10_000)
    offer = _offer(fid, 4096, _digest(old), _digest(old[:4096]))
    assert validate_resume_offer(offer, data, _digest(data), fid) \
        == (0, "restarted_stale")


def test_resume_offer_corrupt_partial_restarts_clean(rng):
    """Right file, rotten bytes: the prefix digest betrays it."""
    data, fid = rng.randbytes(10_000), b"\x13" * 12
    dg = _digest(data)
    offer = _offer(fid, 4096, dg, _digest(b"bitrot"))
    assert validate_resume_offer(offer, data, dg, fid) \
        == (0, "restarted_corrupt")


def test_resume_offer_cold_and_bogus_offsets(rng):
    data, fid = rng.randbytes(1000), b"\x14" * 12
    dg = _digest(data)
    assert validate_resume_offer(_offer(fid, 0, b"", b""),
                                 data, dg, fid) == (0, "cold")
    # an offset past the file can never be a usable prefix
    assert validate_resume_offer(_offer(fid, 2000, dg, dg),
                                 data, dg, fid) == (0, "cold")


def test_resume_offer_rejects_wrong_kind_and_file_id(rng):
    data, fid = rng.randbytes(1000), b"\x15" * 12
    dg = _digest(data)
    with pytest.raises(P2PError, match="different file id"):
        validate_resume_offer(_offer(b"\x16" * 12, 0, b"", b""),
                              data, dg, fid)
    wrong_kind = wire.P2PBody(
        kind=wire.P2PBodyKind.FILE,
        header=wire.P2PHeader(sequence_number=1, session_nonce=NONCE),
        file_info=K, file_id=fid, data=b"x")
    with pytest.raises(P2PError, match="RESUME_OFFER"):
        validate_resume_offer(wrong_kind, data, dg, fid)


# --- transport chokepoint + deadlines (fake socket) -------------------------


class _FakeWS:
    def __init__(self):
        self.sent = []
        self.closed = False

    async def send(self, raw):
        self.sent.append(raw)

    async def close(self):
        self.closed = True


def _fake_transport() -> Transport:
    keys = KeyManager.from_secret(b"\x05" * 32)
    return Transport(_FakeWS(), keys, b"\x07" * 32, NONCE)


def test_send_body_routes_through_fault_chokepoint(loop):
    """Satellite-1 regression: control frames (send_body) leave through
    the SAME chokepoint as FILE frames — an armed drop site severs them
    too, so no traffic is chaos-immune."""
    t = _fake_transport()
    site = f"send.drop:{t.peer_id.hex()}"
    plane = faults.install(faults.FaultPlane(seed=3))
    try:
        plane.arm(site, 0)
        body = wire.P2PBody(
            kind=wire.P2PBodyKind.RESUME_QUERY,
            header=wire.P2PHeader(sequence_number=1, session_nonce=NONCE),
            file_info=K, file_id=b"\x01" * 12)
        with pytest.raises(P2PError, match="injected connection drop"):
            loop.run_until_complete(t.send_body(body))
        assert plane.fired.get(site) == 1
        assert t.ws.closed and not t.ws.sent
    finally:
        faults.uninstall()
    # and with no plane installed it ships, counted as bytes on the wire
    t2 = _fake_transport()
    before = _fam_total("bkw_p2p_bytes_sent_total")
    loop.run_until_complete(t2.send_body(body))
    assert len(t2.ws.sent) == 1
    assert _fam_total("bkw_p2p_bytes_sent_total") \
        == before + len(t2.ws.sent[0])


def test_legacy_ack_deadline_scales_with_payload(loop, monkeypatch):
    """Satellite 3: with a tiny flat ACK_TIMEOUT_S, a large FILE frame
    still gets an ack budget proportional to its size — the same ack
    arriving late passes for the big payload and stalls the small one."""
    monkeypatch.setattr(defaults, "ACK_TIMEOUT_S", 0.05)
    t = _fake_transport()
    big = b"\x5a" * (128 << 10)  # deadline 0.05 + 128Ki/256Ki = 0.55 s

    async def ack(seq: int, delay: float):
        while seq not in t._acks:
            await asyncio.sleep(0.005)
        await asyncio.sleep(delay)
        t._acks[seq].set()

    async def run_ok():
        task = asyncio.create_task(ack(1, 0.2))
        await t.send_data(big, K, b"\x01" * 12)
        await task

    loop.run_until_complete(run_ok())

    stalls = _fam_total("bkw_transfer_stalls_total")

    async def run_stall():
        with pytest.raises(P2PError, match="ack stalled"):
            await t.send_data(b"tiny", K, b"\x02" * 12)

    loop.run_until_complete(run_stall())
    assert _fam_total("bkw_transfer_stalls_total") == stalls + 1


def test_sequence_break_counts_journals_and_closes(loop):
    """Satellite 2: replay protection tripping is not a silent hang —
    the receiver counts it, closes the transport, and errors out."""
    t = _fake_transport()
    body = wire.P2PBody(
        kind=wire.P2PBodyKind.FILE,
        header=wire.P2PHeader(sequence_number=7, session_nonce=NONCE),
        file_info=K, file_id=b"\x01" * 12, data=b"zz")
    sunk = []

    async def sink(kind, fid, data):
        sunk.append(fid)

    async def run():
        await t._recv_queue.put(body)
        before = _fam_total("bkw_p2p_sequence_breaks_total")
        with pytest.raises(P2PError, match="sequence break"):
            await Receiver(t, sink).run()
        assert _fam_total("bkw_p2p_sequence_breaks_total") == before + 1

    loop.run_until_complete(run())
    assert t.ws.closed and not sunk


def test_flaky_reconnect_site_refuses_dial(tmp_path, loop):
    keys = KeyManager.from_secret(b"\x09" * 32)
    store = Store(tmp_path / "cfg", data_base=tmp_path / "data")

    class _ServerStub:  # P2PNode only assigns push handlers onto it
        pass

    node = P2PNode(keys, store, _ServerStub())
    peer = b"\x0a" * 32
    site = f"dial.flaky:{peer.hex()}"
    plane = faults.install(faults.FaultPlane(seed=5))
    try:
        plane.arm(site, 0)
        with pytest.raises(P2PError, match="flaky reconnect"):
            loop.run_until_complete(node.connect(
                peer, wire.RequestType.TRANSPORT, timeout=0.5))
        assert plane.fired.get(site) == 1
    finally:
        faults.uninstall()
        store.close()


# --- capacity-aware placement -----------------------------------------------


def test_placement_orders_by_measured_capacity(tmp_path):
    store = Store(tmp_path / "cfg", data_base=tmp_path / "data")
    fast, slow, fresh = b"\xaa" * 32, b"\xbb" * 32, b"\xcc" * 32
    store.add_peer_negotiated(fast, 10_000_000)
    store.add_peer_negotiated(slow, 20_000_000)  # most free space
    store.add_peer_negotiated(fresh, 5_000_000)
    now = time.time()
    store.put_peer_stats(PeerStatsRow(fast, 50e6, 0.01, 1.0, 10, now))
    store.put_peer_stats(PeerStatsRow(slow, 1e5, 0.5, 0.5, 10, now))
    order = [p.pubkey for p in store.find_peers_with_storage()]
    # measured-fast first despite the least free space; the unmeasured
    # newcomer scores the neutral floor, above the measured-slow peer
    assert order == [fast, fresh, slow]
    store.close()


def test_placement_demotion_excludes_and_probation_recovers(tmp_path):
    store = Store(tmp_path / "cfg", data_base=tmp_path / "data")
    peer = b"\xdd" * 32
    store.add_peer_negotiated(peer, 1_000_000)
    store.set_placement_demoted(peer, True)
    assert peer in store.placement_demoted_peers()
    assert peer not in [p.pubkey for p in store.find_peers_with_storage()]
    # distinct from audit demotion: the probation window re-admits it
    store.set_placement_demoted(
        peer, True, now=time.time() - defaults.PLACEMENT_PROBATION_S - 1)
    assert peer not in store.placement_demoted_peers()
    assert peer in [p.pubkey for p in store.find_peers_with_storage()]
    store.close()


@dataclass
class _Result:
    peer_id: bytes
    size: int
    ok: bool
    wait_s: float = 0.0
    send_s: float = 0.1


def test_peer_stats_demote_on_failures_recover_on_successes(tmp_path):
    store = Store(tmp_path / "cfg", data_base=tmp_path / "data")
    ps = PeerStats(store, alpha=0.5)
    peer = b"\xee" * 32
    demotes = _fam_total("bkw_placement_demotions_total", action="demote")
    for _ in range(defaults.PLACEMENT_DEMOTE_MIN_SAMPLES + 2):
        ps.observe(_Result(peer, 1000, False))
    assert peer in store.placement_demoted_peers()
    assert _fam_total("bkw_placement_demotions_total",
                      action="demote") == demotes + 1
    for _ in range(8):
        ps.observe(_Result(peer, 1000, True))
    assert peer not in store.placement_demoted_peers()
    store.close()


# --- loopback e2e: chunked transfer + crash-resume --------------------------


async def _make_node(tmp_path, name, port):
    keys = KeyManager.from_secret(
        bytes([len(name)]) * 31 + name.encode()[:1])
    store = Store(tmp_path / name / "cfg")
    store.set_obfuscation_key(b"\x11\x22\x33\x44")
    client = ServerClient(keys, store, addr=f"127.0.0.1:{port}")
    await client.register()
    await client.login()
    node = P2PNode(keys, store, client)
    client.start_ws()
    await asyncio.wait_for(client.ws_connected.wait(), 5)
    return keys, store, client, node


def _resumable_receiver(store, source, transport) -> Receiver:
    writer = ReceivedFilesWriter(store, source)
    return Receiver(transport, writer.sink, part_sink=writer.sink_part,
                    resume_query=writer.resume_offer)


def test_chunked_transfer_roundtrip(tmp_path, loop, monkeypatch, rng):
    monkeypatch.setenv("DATA_DIR", str(tmp_path / "b" / "data"))
    monkeypatch.setattr(defaults, "TRANSFER_CHUNK_BYTES", 4096)

    async def run():
        server = CoordinationServer()
        port = await server.start()
        ka, sa, ca, na = await _make_node(tmp_path, "a", port)
        kb, sb, cb, nb = await _make_node(tmp_path, "b", port)
        sa.add_peer_negotiated(kb.client_id, 10_000_000)
        sb.add_peer_negotiated(ka.client_id, 10_000_000)
        done = asyncio.Event()

        async def on_transport(source, transport):
            await _resumable_receiver(sb, source, transport).run()
            done.set()

        nb.on_transport_request = on_transport
        data, pid = rng.randbytes(20_000), b"\x31" * 12
        parts = _fam_total("bkw_transfer_parts_total")
        t = await na.connect(kb.client_id, wire.RequestType.TRANSPORT)
        prog = SendProgress()
        await t.send_file(data, K, pid, progress=prog)
        await t.close()
        await asyncio.wait_for(done.wait(), 10)
        assert (prog.started, prog.offset) == (0, len(data))
        assert _fam_total("bkw_transfer_parts_total") - parts == 5
        writer = ReceivedFilesWriter(sb, ka.client_id)
        assert {s[1]: s[2] for s in writer.iter_stored()} == {pid: data}
        # quota counted once for the assembled file, no partial left over
        assert sb.get_peer(ka.client_id).bytes_received == len(data)
        assert writer.partials.query(pid) == (0, b"", b"")
        await ca.close()
        await cb.close()
        await server.stop()

    loop.run_until_complete(asyncio.wait_for(run(), 60))


def test_crash_cut_resumes_from_verified_offset(tmp_path, loop,
                                                monkeypatch, rng):
    """Satellite 4 e2e: an armed exact-offset cut kills the connection
    mid-transfer; the reconnected sender resumes from the receiver's
    verified partial — re-sent bytes ≪ the file, assembled bytes exact."""
    monkeypatch.setenv("DATA_DIR", str(tmp_path / "b" / "data"))
    monkeypatch.setattr(defaults, "TRANSFER_CHUNK_BYTES", 4096)
    plane = faults.install(faults.FaultPlane(seed=7))
    try:
        async def run():
            server = CoordinationServer()
            port = await server.start()
            ka, sa, ca, na = await _make_node(tmp_path, "a", port)
            kb, sb, cb, nb = await _make_node(tmp_path, "b", port)
            sa.add_peer_negotiated(kb.client_id, 10_000_000)
            sb.add_peer_negotiated(ka.client_id, 10_000_000)

            async def on_transport(source, transport):
                try:
                    await _resumable_receiver(sb, source, transport).run()
                except P2PError:
                    pass  # the severed session may end mid-frame

            nb.on_transport_request = on_transport
            data, pid = rng.randbytes(20_000), b"\x41" * 12
            plane.arm_cut(kb.client_id, 6000)
            t = await na.connect(kb.client_id, wire.RequestType.TRANSPORT)
            prog = SendProgress()
            with pytest.raises(P2PError, match="mid-transfer cut"):
                await t.send_file(data, K, pid, progress=prog)
            assert prog.offset == 4096  # one part landed before the cut
            await asyncio.sleep(0.2)
            writer = ReceivedFilesWriter(sb, ka.client_id)
            assert writer.partials.query(pid)[0] == 4096  # survived crash

            resumed = _fam_total("bkw_transfer_resumes_total",
                                 outcome="resumed")
            t2 = await na.connect(kb.client_id, wire.RequestType.TRANSPORT)
            prog2 = SendProgress()
            await t2.send_file(data, K, pid, progress=prog2)
            await t2.close()
            # resumed exactly at the verified offset: only the tail moved
            assert (prog2.started, prog2.offset) == (4096, len(data))
            assert _fam_total("bkw_transfer_resumes_total",
                              outcome="resumed") == resumed + 1
            assert {s[1]: s[2] for s in writer.iter_stored()} == {pid: data}
            assert sb.get_peer(ka.client_id).bytes_received == len(data)
            await ca.close()
            await cb.close()
            await server.stop()

        loop.run_until_complete(asyncio.wait_for(run(), 60))
    finally:
        faults.uninstall()


def test_tampered_partial_restarts_clean_end_to_end(tmp_path, loop,
                                                    monkeypatch, rng):
    """A receiver partial corrupted between sessions must NOT be resumed:
    the sender's prefix check restarts from zero and the file still
    arrives bit-exact."""
    monkeypatch.setenv("DATA_DIR", str(tmp_path / "b" / "data"))
    monkeypatch.setattr(defaults, "TRANSFER_CHUNK_BYTES", 4096)
    plane = faults.install(faults.FaultPlane(seed=9))
    try:
        async def run():
            server = CoordinationServer()
            port = await server.start()
            ka, sa, ca, na = await _make_node(tmp_path, "a", port)
            kb, sb, cb, nb = await _make_node(tmp_path, "b", port)
            sa.add_peer_negotiated(kb.client_id, 10_000_000)
            sb.add_peer_negotiated(ka.client_id, 10_000_000)

            async def on_transport(source, transport):
                try:
                    await _resumable_receiver(sb, source, transport).run()
                except P2PError:
                    pass

            nb.on_transport_request = on_transport
            data, pid = rng.randbytes(20_000), b"\x51" * 12
            plane.arm_cut(kb.client_id, 6000)
            t = await na.connect(kb.client_id, wire.RequestType.TRANSPORT)
            with pytest.raises(P2PError, match="mid-transfer cut"):
                await t.send_file(data, K, pid)
            await asyncio.sleep(0.2)

            # bitrot the staged partial on the receiver's disk
            bin_p = sb.received_dir(ka.client_id) / "partial" \
                / f"{pid.hex()}.bin"
            blob = bytearray(bin_p.read_bytes())
            blob[100] ^= 0xFF
            bin_p.write_bytes(bytes(blob))

            corrupt = _fam_total("bkw_transfer_resumes_total",
                                 outcome="restarted_corrupt")
            t2 = await na.connect(kb.client_id, wire.RequestType.TRANSPORT)
            prog = SendProgress()
            await t2.send_file(data, K, pid, progress=prog)
            await t2.close()
            assert (prog.started, prog.offset) == (0, len(data))
            assert _fam_total("bkw_transfer_resumes_total",
                              outcome="restarted_corrupt") == corrupt + 1
            writer = ReceivedFilesWriter(sb, ka.client_id)
            assert {s[1]: s[2] for s in writer.iter_stored()} == {pid: data}
            await ca.close()
            await cb.close()
            await server.stop()

        loop.run_until_complete(asyncio.wait_for(run(), 60))
    finally:
        faults.uninstall()
