"""ServerStore ABC conformance, run against every implementation.

One parameterized suite over `SqliteServerStore` (single file),
`PartitionedServerStore` (hash-partitioned files), and
`ReplicatedServerStore` (partitioned + op-log, standalone topology) —
the contract a future PostgreSQL twin must slot in behind:

* write futures resolve only AFTER the row is durable (an independent
  reader over the same files sees it, no flush required);
* `close()` drains the write-behind queue — every accepted write is
  committed or loudly failed before close returns — and is idempotent,
  with the connection left readable for post-stop forensics;
* fan-out reads (`get_clients_storing_on`, `audit_failing_reporters`)
  merge across partitions with distinct/sum semantics, and
  `reclaim_negotiation` retires both directions of an edge wherever
  the two pubkeys hash;
* group commits happen off the caller's thread (`commit_threads`).

Pubkeys are built so `i` lands on partition ``i % partitions`` (8-byte
big-endian prefix), letting every cross-partition case pick its keys
deliberately.
"""

import threading

import pytest

from backuwup_tpu.net.serverstore import (PartitionedServerStore,
                                          ReplicatedServerStore,
                                          ServerStore, SqliteServerStore)

pytestmark = pytest.mark.federation

PARTS = 4
MIB = 1024 * 1024


def pk(i: int) -> bytes:
    return i.to_bytes(8, "big") + bytes(24)


def _mk(kind, root):
    if kind == "sqlite":
        return SqliteServerStore(str(root / "s.db"))
    if kind == "partitioned":
        return PartitionedServerStore(root / "p", partitions=PARTS)
    return ReplicatedServerStore(root / "r", node_id="n0",
                                 partitions=PARTS)


@pytest.fixture(params=["sqlite", "partitioned", "replicated"])
def kind(request):
    return request.param


@pytest.fixture
def store(kind, tmp_path):
    s = _mk(kind, tmp_path)
    yield s
    s.close()


def test_implements_the_abc(store):
    assert isinstance(store, ServerStore)
    assert store.schema_version() >= 1


def test_register_exists_and_login(store):
    assert not store.client_exists(pk(1))
    store.register_client(pk(1))
    store.client_update_logged_in(pk(1))
    assert store.client_exists(pk(1))
    assert not store.client_exists(pk(2))


def test_resolved_write_is_durable_before_flush(kind, store, tmp_path):
    """The durability barrier: when a write call returns (its future
    resolved), an INDEPENDENT store over the same files must already
    see the row — no flush(), no close()."""
    for i in range(PARTS):
        store.register_client(pk(i))
        store.save_storage_negotiated(pk(i), pk(i + PARTS), MIB)
    reader = _mk(kind, tmp_path)
    try:
        for i in range(PARTS):
            assert reader.client_exists(pk(i))
            assert reader.get_client_negotiated_peers(pk(i)) \
                == [pk(i + PARTS)]
    finally:
        reader.close()


def test_snapshot_latest_wins(store):
    store.save_snapshot(pk(1), b"\x0a" * 32)
    store.save_snapshot(pk(1), b"\x0b" * 32)
    assert store.get_latest_client_snapshot(pk(1)) == b"\x0b" * 32
    assert store.get_latest_client_snapshot(pk(2)) is None


def test_fan_out_reads_merge_distinct_across_partitions(store):
    """`get_clients_storing_on` visits every partition (rows home on
    the SOURCE pubkey) and must return each storer once, while
    `get_client_negotiated_peers` stays single-partition."""
    storers = [pk(1), pk(2), pk(3)]  # three different partitions
    for s in storers:
        store.save_storage_negotiated(s, pk(0), MIB)
    store.save_storage_negotiated(pk(0), pk(5), 2 * MIB)
    got = store.get_clients_storing_on(pk(0))
    assert sorted(got) == sorted(storers)
    assert store.get_client_negotiated_peers(pk(0)) == [pk(5)]


def test_reclaim_retires_both_directions(store):
    """One reclaim call must delete the a->b and b->a edges even though
    the two rows live in two different partitions."""
    store.save_storage_negotiated(pk(1), pk(2), MIB)
    store.save_storage_negotiated(pk(2), pk(1), MIB)
    assert store.reclaim_negotiation(pk(1), pk(2)) == 2
    assert store.get_client_negotiated_peers(pk(1)) == []
    assert store.get_client_negotiated_peers(pk(2)) == []
    assert store.reclaim_negotiation(pk(1), pk(2)) == 0


def test_audit_failing_reporters_sums_partitions(store):
    """Failing-reporter counts sum across partitions (reports home on
    the REPORTER pubkey), and a later pass clears a reporter's vote."""
    for i in (1, 2, 3):
        store.save_audit_report(pk(i), pk(0), False, "missed proof")
    assert store.audit_failing_reporters(pk(0), 60.0) == 3
    store.save_audit_report(pk(2), pk(0), True, "recovered")
    assert store.audit_failing_reporters(pk(0), 60.0) == 2


def test_delete_negotiated_is_exact(store):
    store.save_storage_negotiated(pk(1), pk(2), MIB)
    store.save_storage_negotiated(pk(1), pk(3), MIB)
    store.delete_storage_negotiated(pk(1), pk(2), MIB)
    assert store.get_client_negotiated_peers(pk(1)) == [pk(3)]


def test_commits_run_off_the_caller_thread(store):
    """Write-behind means the caller thread never holds the sqlite
    commit — the event-loop-protection invariant the swarm asserts."""
    store.save_storage_negotiated(pk(1), pk(2), MIB)
    assert store.commit_threads, "no commit thread recorded"
    assert threading.get_ident() not in store.commit_threads


def test_close_drains_then_reads_and_is_idempotent(kind, store):
    """Every write accepted before close() is durable after it; close
    is idempotent; the store stays readable post-close (the server's
    stop path logs schema_version, swarm forensics count rows)."""
    n = 32
    for i in range(n):
        store.register_client(pk(i))
    store.close()
    store.close()
    for i in range(n):
        assert store.client_exists(pk(i))
    assert store.schema_version() >= 1


def test_repeated_flush_is_cheap_and_safe(store):
    store.flush()
    store.register_client(pk(7))
    store.flush()
    store.flush()
    assert store.client_exists(pk(7))
