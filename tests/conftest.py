"""Test harness: force an 8-device virtual CPU mesh before JAX imports.

Multi-chip hardware is not available in CI; sharding correctness is validated
on host-platform virtual devices (SURVEY.md section 7 / the driver's
``dryrun_multichip`` contract).
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
# plaintext loopback for the suite (the reference's local-testing posture,
# docs/src/client.md:22); tests/test_tls.py opts back in with real certs
os.environ.setdefault("USE_TLS", "0")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# The environment's sitecustomize re-pins JAX_PLATFORMS to the hardware
# plugin after env setup; the shared helper re-asserts the env pin.
from backuwup_tpu.utils.platform import pin_platform_from_env

pin_platform_from_env()

# Persistent compilation cache: the blake3/CDC programs are large unrolled
# graphs; caching compiled executables across pytest runs keeps the suite
# fast after the first run.
from backuwup_tpu.utils.jaxcache import enable_compilation_cache

enable_compilation_cache()

import random
import signal
import threading

import numpy as np
import pytest

# Per-test watchdog: pytest-timeout is not installed in this container, so
# a SIGALRM-based hookwrapper stands in for it.  The default stays below
# the CI harness's outer `timeout 870` kill so a single wedged test fails
# with a readable traceback instead of taking the whole run down with it.
_WATCHDOG_DEFAULT_S = 780.0


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long-running test, excluded from the tier-1 run"
        " (-m 'not slow')")
    config.addinivalue_line(
        "markers", "timeout(seconds): per-test watchdog override for the"
        " conftest SIGALRM watchdog")
    config.addinivalue_line(
        "markers", "accel: needs a real accelerator backend; skipped"
        " cleanly when jax runs on the host platform (tier-1 pins"
        " JAX_PLATFORMS=cpu)")
    config.addinivalue_line(
        "markers", "concurrency: deterministic transfer-plane overlap"
        " tests (fault-plane latency/death injection); tier-1 safe")
    config.addinivalue_line(
        "markers", "scenario: composed chaos scenario runs"
        " (scenario/harness.py); the fast seeded ones are tier-1, the"
        " full matrix is also marked slow")
    config.addinivalue_line(
        "markers", "crash: crash-consistency tests (deterministic crash"
        " injection + startup recovery sweep, docs/crash_consistency.md);"
        " the unit recoveries and the representative scenario subset are"
        " tier-1, the full matrix and the kill-9 e2e are also slow")
    config.addinivalue_line(
        "markers", "swarm: coordination-plane swarm runs (scenario/"
        "swarm.py); the ~32-client acceptance run is tier-1, the full"
        " load shape is also marked slow")
    config.addinivalue_line(
        "markers", "federation: multi-node coordination-plane tests"
        " (net/ring.py, PartitionedServerStore, cross-node work"
        " stealing, client failover); the ring/store units and the"
        " 3-node kill/revive churn swarm are tier-1, the soak is slow")
    config.addinivalue_line(
        "markers", "tiered: tiered dedup index tests (dedupstore/ hot"
        " HBM probe over the LSM cold tier, docs/dedup_tiering.md); the"
        " units and the 1e6-fingerprint parity gate are tier-1, the"
        " 1e8 soak is also marked slow")
    config.addinivalue_line(
        "markers", "replication: replicated coordination-metadata tests"
        " (op-log shipping, epoch fencing, promote-on-death,"
        " docs/server.md §Replication); the protocol units and the"
        " 3-node permakill swarm are tier-1, the soak and the kill-9"
        " promote e2e are also marked slow")
    config.addinivalue_line(
        "markers", "profile: timing-sensitive profiling tests"
        " (obs/profile.py dev timer); excluded from tier-1 like accel —"
        " set BKW_PROFILE_TESTS=1 to run them")
    config.addinivalue_line(
        "markers", "dataflow: streaming backup dataflow tests (bounded"
        " inter-stage queues, backpressure, event-driven seal->send"
        " wakeup, phased-vs-stream parity, docs/dataflow.md); all"
        " tier-1")
    config.addinivalue_line(
        "markers", "sim: virtual-clock simulation-plane tests"
        " (backuwup_tpu/sim, docs/simulation.md); the 10^5-client"
        " simulated-week builtin is tier-1, the 10^6 soak is also"
        " marked slow")
    config.addinivalue_line(
        "markers", "slo: live SLO-plane tests (obs/series.py burn-rate"
        " windows, obs/slo.py multi-window gating, obs/diagnose.py"
        " ranked explainer, docs/observability.md §SLOs); all tier-1")


def pytest_collection_modifyitems(config, items):
    """Device-only tests (``@pytest.mark.accel``) skip on the CPU host
    platform instead of failing — mirroring the runtime-probe skip the
    blake3 device tests use, but declaratively."""
    if os.environ.get("BKW_PROFILE_TESTS", "") != "1":
        skip_profile = pytest.mark.skip(
            reason="profile-marked timing test (BKW_PROFILE_TESTS=1 to"
            " run)")
        for item in items:
            if item.get_closest_marker("profile"):
                item.add_marker(skip_profile)
    import jax
    if jax.default_backend() != "cpu":
        return
    skip = pytest.mark.skip(reason="accel-marked test: no accelerator"
                            " backend (JAX_PLATFORMS=cpu)")
    for item in items:
        if item.get_closest_marker("accel"):
            item.add_marker(skip)


@pytest.hookimpl(hookwrapper=True)
def pytest_runtest_call(item):
    marker = item.get_closest_marker("timeout")
    limit = float(marker.args[0]) if marker and marker.args \
        else _WATCHDOG_DEFAULT_S
    # SIGALRM only fires in the main thread; under xdist/others, skip.
    use_alarm = (threading.current_thread() is threading.main_thread()
                 and hasattr(signal, "SIGALRM") and limit > 0)
    if use_alarm:
        def on_alarm(signum, frame):
            raise TimeoutError(
                f"test exceeded the {limit:.0f}s conftest watchdog"
                " (mark with @pytest.mark.timeout(N) to override)")

        previous = signal.signal(signal.SIGALRM, on_alarm)
        signal.setitimer(signal.ITIMER_REAL, limit)
    try:
        yield
    finally:
        if use_alarm:
            signal.setitimer(signal.ITIMER_REAL, 0)
            signal.signal(signal.SIGALRM, previous)


def pallas_interpret_works() -> bool:
    """Probe interpret-mode availability with a TRIVIAL kernel so real
    kernel bugs in the interpret test modules fail instead of skipping
    (shared by test_scan_fused_v2 / test_blake3_pallas_interpret)."""
    try:
        import jax
        import jax.numpy as jnp
        from jax.experimental import pallas as pl
    except Exception:  # pragma: no cover
        return False

    def k(o_ref):
        o_ref[...] = jnp.ones_like(o_ref)

    try:
        out = pl.pallas_call(
            k, out_shape=jax.ShapeDtypeStruct((8, 128), jnp.uint32),
            interpret=True)()
        return bool(np.asarray(out).all())
    except Exception:  # pragma: no cover - interpreter gap on this host
        return False


@pytest.fixture
def rng():
    return random.Random(1234)


@pytest.fixture
def nprng():
    return np.random.default_rng(1234)
