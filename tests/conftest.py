"""Test harness: force an 8-device virtual CPU mesh before JAX imports.

Multi-chip hardware is not available in CI; sharding correctness is validated
on host-platform virtual devices (SURVEY.md section 7 / the driver's
``dryrun_multichip`` contract).
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
# plaintext loopback for the suite (the reference's local-testing posture,
# docs/src/client.md:22); tests/test_tls.py opts back in with real certs
os.environ.setdefault("USE_TLS", "0")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# The environment's sitecustomize re-pins JAX_PLATFORMS to the hardware
# plugin after env setup; the shared helper re-asserts the env pin.
from backuwup_tpu.utils.platform import pin_platform_from_env

pin_platform_from_env()

# Persistent compilation cache: the blake3/CDC programs are large unrolled
# graphs; caching compiled executables across pytest runs keeps the suite
# fast after the first run.
from backuwup_tpu.utils.jaxcache import enable_compilation_cache

enable_compilation_cache()

import random

import numpy as np
import pytest


@pytest.fixture
def rng():
    return random.Random(1234)


@pytest.fixture
def nprng():
    return np.random.default_rng(1234)
