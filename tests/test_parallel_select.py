"""Parallel (jump-table) cut selection must equal the sequential oracle.

Stress surface: forced-cut runs (zero/constant regions have no gear
candidates, so every cut is forced at max_size), alignment-dependent
probe retries (periodic data), candidate-dense and candidate-free mixes,
short tails, and multiple parameter sets including the 64 KiB profile
whose sequential while_loop this replaces.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from backuwup_tpu.ops import cdc_cpu
from backuwup_tpu.ops.cdc_tpu import _HALO, scan_select_batch
from backuwup_tpu.ops.gear import CDCParams

PARAMS = [
    CDCParams.from_desired(4096),
    CDCParams.from_desired(16384),
    CDCParams(min_size=1024, desired_size=4096, max_size=6144,
              mask_s_bits=14, mask_l_bits=10),
]


def _run_device(data: bytes, params: CDCParams, P: int):
    buf = np.zeros((1, _HALO + P), dtype=np.uint8)
    buf[0, _HALO:_HALO + len(data)] = np.frombuffer(data, dtype=np.uint8)
    l_cap = max(512, ((16 * max(1, P >> params.mask_l_bits)) + 511)
                // 512 * 512)
    cut_cap = P // params.min_size + 1
    packed = scan_select_batch(
        jnp.asarray(buf), jnp.asarray(np.array([len(data)], np.int32)),
        min_size=params.min_size, desired_size=params.desired_size,
        max_size=params.max_size, mask_s=params.mask_s,
        mask_l=params.mask_l, s_cap=l_cap, l_cap=l_cap, cut_cap=cut_cap,
        fused=False)
    row = np.asarray(packed)[0]
    assert row[0] == 0, "unexpected overflow/unresolved on test data"
    n_cuts = int(row[1])
    ends = row[2:2 + n_cuts].astype(np.int64)
    offs = np.concatenate([[0], ends[:-1] + 1])
    return list(zip(offs.tolist(), (ends - offs + 1).tolist()))


def _corpora(rng, n):
    yield "random", rng.integers(0, 256, n, dtype=np.uint8).tobytes()
    yield "zeros", b"\0" * n
    yield "const", b"\x5a" * n
    # periodic: candidate positions repeat with the period, the
    # alignment-retry path of the closed-form forced jump
    pat = rng.integers(0, 256, 4096, dtype=np.uint8).tobytes()
    yield "periodic", (pat * (n // len(pat) + 1))[:n]
    # half zeros then random: a long candidate-free gap mid-stream
    half = rng.integers(0, 256, n - n // 2, dtype=np.uint8).tobytes()
    yield "gap", b"\0" * (n // 2) + half
    # random with zero windows sprinkled in
    mixed = bytearray(rng.integers(0, 256, n, dtype=np.uint8).tobytes())
    for off in range(0, n - 8192, 37 * 1024):
        mixed[off:off + 8192] = b"\0" * 8192
    yield "sprinkled", bytes(mixed)


@pytest.mark.parametrize("params", PARAMS)
def test_parallel_select_matches_oracle(params):
    rng = np.random.default_rng(99)
    P = 1 << 20
    for tag, data in _corpora(rng, P):
        got = _run_device(data, params, P)
        want = cdc_cpu.chunk_stream(data, params)
        assert got == want, f"{tag} @ desired={params.desired_size}"


@pytest.mark.parametrize("n", [0, 1, 1023, 1024, 1025, 4095, 4096, 65535])
def test_parallel_select_sizes(n):
    params = CDCParams.from_desired(4096)
    data = np.random.default_rng(n or 5).integers(
        0, 256, n, dtype=np.uint8).tobytes()
    got = _run_device(data, params, 65536)
    assert got == cdc_cpu.chunk_stream(data, params)
