"""Windowed Gear CDC: scalar-oracle vs vectorized parity, spec edge cases."""

import numpy as np
import pytest

from backuwup_tpu.ops.cdc_cpu import (candidate_positions, chunk_stream,
                                      chunk_stream_scalar, gear_hashes,
                                      gear_hashes_scalar, select_cuts)
from backuwup_tpu.ops.gear import GEAR, CDCParams

SMALL = CDCParams.from_desired(1024)  # min 256 / desired 1024 / max 3072


def test_gear_table_properties():
    assert GEAR.shape == (256,) and GEAR.dtype == np.uint32
    assert len(set(GEAR.tolist())) == 256  # no collisions in the table
    # regression pin: table is deterministic data, not environment-dependent
    assert GEAR[0] == np.uint32(0xD5237E27), hex(int(GEAR[0]))
    assert GEAR[1] == np.uint32(0xAE4C672E), hex(int(GEAR[1]))


def test_gear_hash_scalar_vs_vectorized(nprng):
    data = nprng.integers(0, 256, size=5000, dtype=np.uint8).tobytes()
    np.testing.assert_array_equal(gear_hashes_scalar(data), gear_hashes(data))


def test_gear_hash_halo_equivalence(nprng):
    data = nprng.integers(0, 256, size=4096, dtype=np.uint8).tobytes()
    full = gear_hashes(data)
    for split in (0, 1, 17, 31, 32, 33, 1000, 4095, 4096):
        left, right = data[:split], data[split:]
        got = np.concatenate([gear_hashes(left),
                              gear_hashes(right, prev_tail=left)])
        np.testing.assert_array_equal(full, got, err_msg=f"split={split}")


def test_chunk_scalar_vs_vectorized(nprng):
    for size in (0, 1, 255, 256, 257, 1024, 3072, 3073, 50_000, 200_000):
        data = nprng.integers(0, 256, size=size, dtype=np.uint8).tobytes()
        assert chunk_stream(data, SMALL) == chunk_stream_scalar(data, SMALL), size


def test_chunks_partition_stream(nprng):
    data = nprng.integers(0, 256, size=300_000, dtype=np.uint8).tobytes()
    chunks = chunk_stream(data, SMALL)
    assert sum(c[1] for c in chunks) == len(data)
    pos = 0
    for off, ln in chunks:
        assert off == pos
        assert 1 <= ln <= SMALL.max_size
        pos = off + ln
    # all but the final chunk respect the minimum
    assert all(ln >= SMALL.min_size for _, ln in chunks[:-1])


def test_low_entropy_forces_max_cuts():
    data = b"\x00" * 10_000
    chunks = chunk_stream(data, SMALL)
    # constant input yields no candidates -> forced cuts at max, runt at EOF
    assert [ln for _, ln in chunks] == [3072, 3072, 3072, 784]


def test_insertion_resync(nprng):
    """Window-local hashing re-synchronizes after an insertion."""
    data = nprng.integers(0, 256, size=400_000, dtype=np.uint8).tobytes()
    mutated = data[:200_000] + b"INSERTED" + data[200_000:]
    a = {data[o:o + l] for o, l in chunk_stream(data, SMALL)}
    b = {mutated[o:o + l] for o, l in chunk_stream(mutated, SMALL)}
    # chunks strictly before the edit and well after it must be shared
    assert len(a & b) >= len(a) // 2


def test_select_cuts_eof_runt():
    params = SMALL
    # no candidates at all: pure min/max geometry
    ends = select_cuts(np.empty(0, np.int64), np.empty(0, np.int64),
                       7000, params)
    assert ends.tolist() == [3071, 6143, 6999]
    # empty stream
    assert select_cuts(np.empty(0, np.int64), np.empty(0, np.int64),
                       0, params).tolist() == []


def test_candidate_subset_property(nprng):
    data = nprng.integers(0, 256, size=100_000, dtype=np.uint8).tobytes()
    pos_s, pos_l = candidate_positions(data, SMALL)
    assert set(pos_s.tolist()) <= set(pos_l.tolist())


def test_params_validation():
    with pytest.raises(ValueError):
        CDCParams(min_size=10, desired_size=5, max_size=20)
    with pytest.raises(ValueError):
        CDCParams.from_desired(1000)  # not a power of two
    p = CDCParams.from_desired(8192)
    assert (p.min_size, p.desired_size, p.max_size) == (2048, 8192, 24576)
    assert p.mask_s_bits == 15 and p.mask_l_bits == 11
