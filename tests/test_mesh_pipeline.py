"""Mesh manifest plane: shard-mapped scan->digest must be bit-identical.

Parity posture (ISSUE 12 / parity ladder): a mesh that mis-lowers loses
speed, never correctness — so every test here pins bit-exact equality
against BOTH the single-device driver and the CPU oracle, across
parameter sets and 1/2/8-device meshes (tests/conftest.py forces
``--xla_force_host_platform_device_count=8``).  The dispatch-contract
tests hand-count launches per the obs/profile.py table: one shard_map
program counts ONCE per stage unlabeled plus once per participating
device in ``bkw_mesh_device_dispatch_total``.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from backuwup_tpu.crypto import KeyManager
from backuwup_tpu.obs import profile
from backuwup_tpu.ops import cdc_cpu
from backuwup_tpu.ops.blake3_cpu import Blake3Numpy
from backuwup_tpu.ops.cdc_tpu import _HALO
from backuwup_tpu.ops.gear import CDCParams
from backuwup_tpu.ops.pipeline import DevicePipeline
from backuwup_tpu.snapshot.blob_index import BlobIndex
from backuwup_tpu.snapshot.device_dedup import MeshDedupIndex

SMALL = CDCParams.from_desired(4096)
PARAM_SETS = [CDCParams.from_desired(d) for d in (4096, 8192, 16384)]


def _oracle(data, params):
    chunks = cdc_cpu.chunk_stream(data, params)
    digests = Blake3Numpy().digest_batch([data[o:o + l] for o, l in chunks])
    return chunks, digests


def _stage(rows, P):
    buf = np.zeros((len(rows), _HALO + P), dtype=np.uint8)
    nv = np.zeros(len(rows), dtype=np.int32)
    for r, d in enumerate(rows):
        buf[r, _HALO:_HALO + len(d)] = np.frombuffer(d, dtype=np.uint8)
        nv[r] = len(d)
    return buf, nv


def _mesh(n):
    return Mesh(np.array(jax.devices()[:n]), ("data",))


# The single-device 8K/16K legs ride the slow tier: tier-1 keeps the
# full multi-device matrix plus the 4096 single-device leg, which
# already pins the mesh-vs-single parity path — the larger desired
# sizes change only the cut mask, covered by the 2/8-device legs.
# (The tier-1 wall budget is a hard 870 s; see ROADMAP.md.)
@pytest.mark.parametrize(
    "params,n_dev",
    [pytest.param(p, n, id=f"{p.desired_size}-{n}",
                  marks=([pytest.mark.slow]
                         if n == 1 and p.desired_size > 4096 else []))
     for p in PARAM_SETS for n in (1, 2, 8)])
def test_mesh_matches_single_device_and_oracle(params, n_dev):
    P = 65536
    rng = np.random.default_rng(13 * n_dev + params.desired_size)
    rows = [rng.integers(0, 256, n, dtype=np.uint8).tobytes()
            for n in (65536, 30_000, 0, 65536)]
    buf, nv = _stage(rows, P)
    single = list(DevicePipeline(params).manifest_segments_device(
        iter([(jnp.asarray(buf), nv)])))[0]
    pipe = DevicePipeline(params, mesh=_mesh(n_dev))
    (mesh_out,) = list(pipe.manifest_segments_mesh(iter([(buf, nv)])))
    assert len(mesh_out) == len(rows)
    for r, data in enumerate(rows):
        s_chunks, s_digs = single[r]
        m_chunks, m_digs = mesh_out[r]
        assert m_chunks == s_chunks
        assert np.array_equal(m_digs, s_digs)
        ref_chunks, ref_digests = _oracle(data, params)
        assert m_chunks == ref_chunks
        assert [bytes(d) for d in m_digs] == ref_digests


def test_mesh_per_shard_overflow_reruns_only_that_shard():
    """All-zero 1 MiB row (chunks entirely at max size) overflows its
    shard's pool; the 7 random shards must NOT re-run.  Hand count:
    unlabeled scan = 1 (the shard_map launch) + 1 (the ONE fallback
    shard's host-tiled re-run); per-device labeled scan = exactly 1
    everywhere (fallback launches are not mesh launches)."""
    P = 1 << 20
    rng = np.random.default_rng(29)
    rows = [b"\0" * P] + [rng.integers(0, 256, P, dtype=np.uint8).tobytes()
                          for _ in range(7)]
    buf, nv = _stage(rows, P)
    pipe = DevicePipeline(SMALL, mesh=_mesh(8))
    if not pipe.pool_digest:
        pytest.skip("leaf-pool digest unavailable on this runtime")
    base = profile.baseline()
    (out,) = list(pipe.manifest_segments_mesh(iter([(buf, nv)])))
    rep = profile.report(base)
    assert rep["dispatches"]["scan"] == 2, \
        "exactly one shard may re-run on the host-tiled path"
    dev = rep["device_dispatches"]
    assert sorted(dev, key=int) == [str(d) for d in range(8)]
    assert all(dev[d]["scan"] == 1 for d in dev)
    # bytes prove which shard fell back: unlabeled scan actual = the mesh
    # launch (8 MiB) + only shard 0's rows again (1 MiB)
    assert rep["bytes"]["scan"] == 8 * P + P
    for r, data in enumerate(rows):
        chunks, digs = out[r]
        ref_chunks, ref_digests = _oracle(data, SMALL)
        assert chunks == ref_chunks
        assert [bytes(d) for d in digs] == ref_digests


def test_mesh_even_split_across_devices():
    P = 65536
    rng = np.random.default_rng(31)
    rows = [rng.integers(0, 256, P, dtype=np.uint8).tobytes()
            for _ in range(16)]
    buf, nv = _stage(rows, P)
    pipe = DevicePipeline(SMALL, mesh=_mesh(8))
    if not pipe.pool_digest:
        pytest.skip("leaf-pool digest unavailable on this runtime")
    base = profile.baseline()
    list(pipe.manifest_segments_mesh(iter([(buf, nv)])))
    rep = profile.report(base)
    dev = rep["device_dispatches"]
    counts = [dev[str(d)]["digest"] for d in range(8)]
    assert max(counts) - min(counts) <= 1
    # equal-length rows: byte shares split exactly evenly too
    for d in range(8):
        assert rep["device_pad_efficiency"][str(d)]["scan"] == \
            rep["device_pad_efficiency"]["0"]["scan"]
    assert pipe.mesh_hbm_high_water and \
        len(set(pipe.mesh_hbm_high_water.values())) == 1


def test_mesh_dedup_handoff_zero_host_roundtrips(tmp_path, monkeypatch):
    """The manifest->dedup handoff must classify whole batches without
    any per-batch host round trip of the fingerprints: with the
    host-side query builder booby-trapped, two overlapping passes must
    still produce correct dup hints, and the index-stage dispatch count
    must equal the number of device batches (the insert_device launches
    ride the dispatch contract, not hashes_to_queries)."""
    P = 65536
    rng = np.random.default_rng(37)
    rows_a = [rng.integers(0, 256, P, dtype=np.uint8).tobytes()
              for _ in range(8)]
    rows_b = rows_a[:4] + [rng.integers(0, 256, P, dtype=np.uint8).tobytes()
                           for _ in range(4)]
    keys = KeyManager.from_secret(b"\x07" * 32)
    host = BlobIndex(keys, tmp_path / "index")
    mesh = _mesh(8)
    dev = MeshDedupIndex(mesh, host)
    pipe = DevicePipeline(SMALL, mesh=mesh)
    if not pipe.pool_digest:
        pytest.skip("leaf-pool digest unavailable on this runtime")

    def _boom(_hashes):
        raise AssertionError("fingerprints crossed the host link")

    monkeypatch.setattr("backuwup_tpu.snapshot.device_dedup."
                        "hashes_to_queries", _boom)

    def classify(rows):
        buf, nv = _stage(rows, P)
        base = profile.baseline()
        ((out, flags),) = list(pipe.manifest_segments_mesh(
            iter([(buf, nv)]), dedup=dev))
        rep = profile.report(base)
        assert rep["dispatches"]["index"] == 1  # one device batch
        assert all(rep["device_dispatches"][str(d)]["index"] == 1
                   for d in range(8))
        hashes, raw = [], []
        for (chunks, digs), fl in zip(out, flags):
            assert fl is not None and len(fl) == len(chunks)
            for k in range(len(chunks)):
                hashes.append(digs[k].tobytes())
                raw.append(bool(fl[k]))
        return hashes, dev.resolve_hints(hashes, raw)

    hashes_a, hints_a = classify(rows_a)
    seen = set()
    for h, hint in zip(hashes_a, hints_a):
        assert hint == (h in seen)
        seen.add(h)
    # pass 2 overlaps pass 1: the repeated rows' chunks are resident in
    # the device table and must classify duplicate; the fresh rows new
    hashes_b, hints_b = classify(rows_b)
    for h, hint in zip(hashes_b, hints_b):
        assert hint == (h in seen)
        seen.add(h)


def test_manifest_many_classified_backend(tmp_path):
    """TpuBackend's fused manifest+classify over mixed stream shapes
    (empty / tiny / batched): hints must match the first-occurrence-new
    rule on an empty index and be all-duplicate on a repeat call."""
    from backuwup_tpu.ops.backend import TpuBackend

    rng = np.random.default_rng(41)
    streams = [b"", b"tiny-blob", rng.integers(
        0, 256, 50_000, dtype=np.uint8).tobytes(),
        rng.integers(0, 256, 20_000, dtype=np.uint8).tobytes()]
    keys = KeyManager.from_secret(b"\x07" * 32)
    host = BlobIndex(keys, tmp_path / "index")
    dev = MeshDedupIndex(_mesh(8), host)
    backend = TpuBackend(SMALL)
    backend.attach_mesh(dev.mesh, dev.axis)
    manifests, hints = backend.manifest_many_classified(streams, dev)
    refs = [r for m in manifests for r in m]
    assert len(hints) == len(refs)
    seen = set()
    for ref, hint in zip(refs, hints):
        assert hint == (ref.hash in seen)
        seen.add(ref.hash)
    # parity with the plain manifest path
    plain = TpuBackend(SMALL).manifest_many(streams)
    assert [[(r.offset, r.length, r.hash) for r in m] for m in manifests] \
        == [[(r.offset, r.length, r.hash) for r in m] for m in plain]
    manifests2, hints2 = backend.manifest_many_classified(streams, dev)
    # device-classified rows are resident from pass 1 -> duplicate; the
    # tiny stream rides the host-authority lane, and the host index has
    # no blobs -> False (hints may only err toward re-storing, never
    # toward skipping a needed store)
    it2 = iter(hints2)
    for m_idx, m in enumerate(manifests2):
        for _ in m:
            assert next(it2) == (m_idx != 1)


def test_nv_cache_is_lru():
    pipe = DevicePipeline(SMALL)
    a = np.arange(4, dtype=np.int32)
    b = np.arange(4, dtype=np.int32) + 1000
    pipe._nv_device(a)
    pipe._nv_device(b)
    pipe._nv_device(a)  # hit: A becomes most-recently-used
    for i in range(62):
        pipe._nv_device(np.full(4, i + 1, dtype=np.int32))
    assert len(pipe._nv_cache) == 64
    pipe._nv_device(np.full(4, 9999, dtype=np.int32))
    assert len(pipe._nv_cache) == 64  # evicts ONE entry, not the world
    assert a.tobytes() in pipe._nv_cache  # hot entry survived
    assert b.tobytes() not in pipe._nv_cache  # coldest entry evicted
