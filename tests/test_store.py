"""Local state store: KV round-trips, peer ledger accounting, event log."""

import pytest

from backuwup_tpu.store import (
    EVENT_BACKUP,
    EVENT_RESTORE_REQUEST,
    Store,
)


@pytest.fixture
def store(tmp_path):
    s = Store(tmp_path / "cfg")
    yield s
    s.close()


def test_identity_round_trip(store):
    assert store.get_root_secret() is None
    assert not store.is_initialized()
    store.set_root_secret(b"\x07" * 32)
    store.set_auth_token(b"\x01" * 16)
    store.set_obfuscation_key(b"\xaa\xbb\xcc\xdd")
    store.set_initialized()
    assert store.get_root_secret() == b"\x07" * 32
    assert store.get_auth_token() == b"\x01" * 16
    assert store.get_obfuscation_key() == b"\xaa\xbb\xcc\xdd"
    assert store.is_initialized()
    store.set_auth_token(None)
    assert store.get_auth_token() is None


def test_obfuscation_key_length_checked(store):
    with pytest.raises(ValueError):
        store.set_obfuscation_key(b"\x01" * 5)


def test_backup_config(store):
    assert store.get_backup_path() is None
    store.set_backup_path("/data/stuff")
    assert store.get_backup_path() == "/data/stuff"
    assert store.get_highest_sent_index() == -1
    store.set_highest_sent_index(17)
    assert store.get_highest_sent_index() == 17


def test_peer_ledger_accounting(store):
    a, b = b"\x01" * 32, b"\x02" * 32
    store.add_peer_negotiated(a, 1000)
    store.add_peer_negotiated(a, 500)   # upsert-increment
    store.add_peer_negotiated(b, 2000)
    store.add_peer_transmitted(a, 300)
    store.add_peer_received(b, 100)
    pa, pb = store.get_peer(a), store.get_peer(b)
    assert pa.bytes_negotiated == 1500 and pa.bytes_transmitted == 300
    assert pa.free_storage == 1200
    assert pb.bytes_received == 100 and pb.free_storage == 2000
    # ordered by free storage, most first
    assert [p.pubkey for p in store.find_peers_with_storage()] == [b, a]


def test_peer_bump_creates_row(store):
    store.add_peer_transmitted(b"\x09" * 32, 42)
    assert store.get_peer(b"\x09" * 32).bytes_transmitted == 42


def test_event_log(store):
    assert store.last_event_time(EVENT_RESTORE_REQUEST) is None
    store.add_event(EVENT_RESTORE_REQUEST, {}, now=100.0)
    store.add_event(EVENT_RESTORE_REQUEST, {}, now=200.0)
    assert store.last_event_time(EVENT_RESTORE_REQUEST) == 200.0
    assert store.last_backup_size() is None
    store.add_event(EVENT_BACKUP, {"size": 12345}, now=300.0)
    assert store.last_backup_size() == 12345


def test_persistence_across_reopen(tmp_path):
    s = Store(tmp_path / "cfg")
    s.set_root_secret(b"\x03" * 32)
    s.add_peer_negotiated(b"\x04" * 32, 777)
    s.close()
    s2 = Store(tmp_path / "cfg")
    assert s2.get_root_secret() == b"\x03" * 32
    assert s2.get_peer(b"\x04" * 32).bytes_negotiated == 777
    s2.close()


def test_tracing_spans_and_report():
    """Host tracing subsystem (SURVEY §5.1: the build adds what the
    reference lacks)."""
    from backuwup_tpu.utils import tracing

    tracing.reset()
    tracing.enable(True)
    try:
        with tracing.span("unit.test"):
            pass

        @tracing.traced("unit.decorated")
        def f():
            return 41

        assert f() == 41
        rep = tracing.report()
        assert rep["unit.test"][0] == 1
        assert rep["unit.decorated"][0] == 1
        assert "unit.test" in tracing.format_report()
    finally:
        tracing.enable(False)
        tracing.reset()
    # disabled: no recording
    with tracing.span("unit.off"):
        pass
    assert "unit.off" not in tracing.report()
