"""Snapshot lifecycle plane: retention, GC, compaction, reclaim.

docs/lifecycle.md in unit form — the pieces below the ``gc`` scenario:

* retention policies (``keep-last:N`` / ``keep-daily:N``) marking, never
  deleting, and never walking past the newest restorable snapshot;
* the snapshot manifest join (``live_blobs``) and the legacy-store
  refusal guard;
* index tombstones surviving a reload (a dropped blob must not
  resurrect through the later-files-win index replay);
* challenge-table cleanup following ``forget_packfiles`` everywhere;
* the RECLAIM wire bodies, the persisted reclaim backlog, and the
  holder-side ``serve_reclaim`` (identity-scoped deletes, quota credit,
  throttle);
* ``run_gc`` end-to-end on an offline engine (drop-only), the
  compaction internals (classify → stage → repack → swap), and per-seam
  crash recovery rolling the state machine back or forward;
* the crash-site registry's completeness against the package tree
  (a ``crashpoint`` call site whose seam is not registered would
  silently escape the crash matrix).
"""

import asyncio
import os
import time
import types
from pathlib import Path

import pytest

import backuwup_tpu
from backuwup_tpu import defaults, wire
from backuwup_tpu.crypto import KeyManager
from backuwup_tpu.engine import Engine
from backuwup_tpu.net.p2p import P2PError, P2PNode
from backuwup_tpu.obs import journal as obs_journal
from backuwup_tpu.obs import metrics as obs_metrics
from backuwup_tpu.obs.invariants import InvariantMonitor
from backuwup_tpu.ops.blake3_cpu import blake3_hash
from backuwup_tpu.erasure.stripe import shard_id
from backuwup_tpu.snapshot.blob_index import BlobIndex, ChallengeEntry, \
    ChallengeTable
from backuwup_tpu.snapshot.packfile import PackfileWriter, packfile_path
from backuwup_tpu.store import Store
from backuwup_tpu.utils import faults
from backuwup_tpu.wire import Blob, BlobKind

pytestmark = pytest.mark.crash

KEYS = KeyManager.from_secret(bytes(range(32)))


@pytest.fixture(autouse=True)
def _isolate():
    obs_metrics.registry().reset()
    yield
    obs_metrics.registry().reset()
    obs_journal.uninstall()
    faults.uninstall()


@pytest.fixture
def loop():
    loop = asyncio.new_event_loop()
    yield loop
    loop.close()


@pytest.fixture
def plane():
    return faults.install(faults.FaultPlane(seed=7))


def _blob(data: bytes) -> Blob:
    return Blob(hash=blake3_hash(data), kind=BlobKind.FILE_CHUNK, data=data)


def _mk_engine(tmp_path):
    store = Store(tmp_path / "cfg", data_base=tmp_path / "data")
    engine = Engine(KEYS, store, None, None)
    engine.auto_repair = False
    return engine, store


def _write_packfile(out_dir, payloads):
    """One sealed packfile holding ``payloads``; (pid, path, hashes)."""
    written = []
    w = PackfileWriter(KEYS, out_dir,
                       on_packfile=lambda pid, path, hashes, size:
                       written.append((pid, path, hashes)))
    for p in payloads:
        w.add_blob(_blob(p))
    w.flush()
    w.close()
    return written[0]


def _snap(store, tag: bytes, parent, payloads, now=None):
    """Record one snapshot whose manifest is ``payloads``' blobs."""
    h = blake3_hash(b"snap:" + tag)
    store.record_snapshot(h, parent, sum(len(p) for p in payloads),
                          [(blake3_hash(p), len(p)) for p in payloads],
                          now=now)
    return h


# --- retention --------------------------------------------------------------


def test_retention_keep_last_marks_never_deletes(tmp_path):
    store = Store(tmp_path / "cfg", data_base=tmp_path / "data")
    try:
        s1 = _snap(store, b"1", None, [b"a"], now=100.0)
        s2 = _snap(store, b"2", s1, [b"b"], now=200.0)
        s3 = _snap(store, b"3", s2, [b"c"], now=300.0)
        assert store.apply_retention("keep-all") == []
        pruned = store.apply_retention("keep-last:2", now=400.0)
        assert pruned == [s1]
        # marked dead, not deleted: lineage survives, retention flips a flag
        assert len(store.list_snapshots()) == 3
        assert [s.hash for s in store.retained_snapshots()] == [s2, s3]
        assert store.latest_snapshot().hash == s3
        # idempotent: the prune set is already pruned
        assert store.apply_retention("keep-last:2", now=401.0) == []
    finally:
        store.close()


def test_retention_always_keeps_the_newest_snapshot(tmp_path):
    store = Store(tmp_path / "cfg", data_base=tmp_path / "data")
    try:
        s1 = _snap(store, b"1", None, [b"a"], now=100.0)
        s2 = _snap(store, b"2", s1, [b"b"], now=200.0)
        # keep-last:0 asks for nothing — the latest survives regardless
        assert store.apply_retention("keep-last:0") == [s1]
        assert [s.hash for s in store.retained_snapshots()] == [s2]
    finally:
        store.close()


def test_retention_keep_daily_keeps_newest_per_day(tmp_path):
    store = Store(tmp_path / "cfg", data_base=tmp_path / "data")
    try:
        day = 86400.0
        s1 = _snap(store, b"1", None, [b"a"], now=0.25 * day)
        s2 = _snap(store, b"2", s1, [b"b"], now=0.75 * day)  # day 0 newest
        s3 = _snap(store, b"3", s2, [b"c"], now=1.5 * day)
        pruned = store.apply_retention("keep-daily:2")
        assert pruned == [s1]
        assert [s.hash for s in store.retained_snapshots()] == [s2, s3]
    finally:
        store.close()


def test_retention_rejects_unknown_and_malformed_rules(tmp_path):
    store = Store(tmp_path / "cfg", data_base=tmp_path / "data")
    try:
        _snap(store, b"1", None, [b"a"])
        with pytest.raises(ValueError):
            store.apply_retention("keep-weekly:2")
        with pytest.raises(ValueError):
            store.apply_retention("keep-last:soon")
        # persisted policy round-trip feeds the default argument
        store.set_retention_policy("keep-last:3")
        assert store.get_retention_policy() == "keep-last:3"
    finally:
        store.close()


def test_live_blobs_joins_retained_manifests_only(tmp_path):
    store = Store(tmp_path / "cfg", data_base=tmp_path / "data")
    try:
        s1 = _snap(store, b"1", None, [b"aaa", b"bb"], now=100.0)
        _snap(store, b"2", s1, [b"bb", b"cccc"], now=200.0)
        assert set(store.live_blobs()) == {blake3_hash(b"aaa"),
                                           blake3_hash(b"bb"),
                                           blake3_hash(b"cccc")}
        store.apply_retention("keep-last:1")
        live = store.live_blobs()
        assert set(live) == {blake3_hash(b"bb"), blake3_hash(b"cccc")}
        assert live[blake3_hash(b"cccc")] == 4
        # the occupancy denominator still sees the pruned manifest...
        assert blake3_hash(b"aaa") in store.manifest_blobs()
        # ...until the post-swap cleanup drops it
        assert store.drop_pruned_manifests() > 0
        assert blake3_hash(b"aaa") not in store.manifest_blobs()
    finally:
        store.close()


# --- index tombstones + challenge-table cleanup -----------------------------


def test_tombstoned_blobs_stay_dead_across_reload(tmp_path):
    idx_dir = tmp_path / "index"
    h = blake3_hash(b"payload")
    pid = b"\x01" * wire.PACKFILE_ID_LEN
    idx = BlobIndex(KEYS, idx_dir)
    idx.finalize_packfile(pid, [h])
    idx.flush()
    lost = idx.forget_packfiles([pid])
    assert h in lost
    idx.record_tombstones([h])
    idx.flush()
    # the replay reads index files oldest-first; without the tombstone
    # the first file's mapping would win the blob back
    fresh = BlobIndex(KEYS, idx_dir)
    fresh.load()
    assert fresh.lookup(h) is None
    assert pid not in fresh.packfile_ids()


def test_challenge_forget_sweeps_whole_file_and_shard_tables(tmp_path):
    ct = ChallengeTable(KEYS, tmp_path)
    entries = [ChallengeEntry(0, 16, b"\x01" * wire.AUDIT_NONCE_LEN,
                              b"\x02" * 32)]
    pid = b"\x7c" * wire.PACKFILE_ID_LEN
    ct.save(pid, entries)
    for idx in range(2):
        ct.save(shard_id(pid, idx), entries)
    other = b"\x7d" * wire.PACKFILE_ID_LEN
    ct.save(other, entries)
    ct.forget([pid])
    assert not ct.has(pid)
    assert not any(ct.has(shard_id(pid, i)) for i in range(2))
    assert ct.has(other)
    ct.forget([pid])  # idempotent
    assert ct.has(other)


# --- RECLAIM wire + backlog + holder side -----------------------------------


def test_reclaim_bodies_roundtrip():
    hdr = wire.P2PHeader(sequence_number=9, session_nonce=b"\x05" * 16)
    req = wire.P2PBody(
        kind=wire.P2PBodyKind.RECLAIM_REQUEST, header=hdr,
        wants=((wire.FileInfoKind.PACKFILE, b"\x01" * wire.PACKFILE_ID_LEN),
               (wire.FileInfoKind.SHARD,
                shard_id(b"\x02" * wire.PACKFILE_ID_LEN, 3))))
    back = wire.P2PBody.decode_bytes(req.encode_bytes())
    assert back == req
    ack = wire.P2PBody(kind=wire.P2PBodyKind.RECLAIM_ACK, header=hdr,
                       acked_sequence=9, offset=4096)
    back = wire.P2PBody.decode_bytes(ack.encode_bytes())
    assert back.acked_sequence == 9 and back.offset == 4096


def test_reclaim_backlog_dedups_and_quota_credit_clamps(tmp_path):
    store = Store(tmp_path / "cfg", data_base=tmp_path / "data")
    try:
        fid, peer = b"\x01" * wire.PACKFILE_ID_LEN, b"\x42" * 32
        store.queue_reclaim(fid, peer, int(wire.FileInfoKind.PACKFILE), 100)
        # re-queue of the same (file, peer) row is a no-op, not a dup
        store.queue_reclaim(fid, peer, int(wire.FileInfoKind.PACKFILE), 100)
        assert store.reclaim_backlog() == [
            (fid, peer, int(wire.FileInfoKind.PACKFILE), 100)]
        assert store.clear_reclaim(fid, peer) == 1
        assert store.reclaim_backlog() == []

        store.add_peer_negotiated(peer, 1000)
        store.add_peer_transmitted(peer, 300)
        store.credit_peer_transmitted(peer, 200)
        assert store.get_peer(peer).bytes_transmitted == 100
        # a replayed ack must not mint quota: clamped at zero
        store.credit_peer_transmitted(peer, 500)
        assert store.get_peer(peer).bytes_transmitted == 0
    finally:
        store.close()


class _FakeTransport:
    """Just enough of Transport for the serve-side handlers."""

    def __init__(self, inbound):
        self.seq = 0
        self.session_nonce = b"\x00" * 16
        self._in = list(inbound)
        self.sent = []

    async def recv_body(self, timeout=None):
        return self._in.pop(0)

    async def send_body(self, body):
        self.sent.append(body)


def _mk_node(tmp_path):
    store = Store(tmp_path / "cfg", data_base=tmp_path / "data")
    store.set_obfuscation_key(b"\x01\x02\x03\x04")
    node = P2PNode(KEYS, store, types.SimpleNamespace())
    return node, store


def _reclaim_body(wants, seq=3):
    return wire.P2PBody(
        kind=wire.P2PBodyKind.RECLAIM_REQUEST,
        header=wire.P2PHeader(sequence_number=seq,
                              session_nonce=b"\x00" * 16),
        wants=tuple(wants))


def test_serve_reclaim_deletes_own_placements_and_credits(tmp_path, loop):
    node, store = _mk_node(tmp_path)
    saved = defaults.RECLAIM_MIN_INTERVAL_S
    defaults.RECLAIM_MIN_INTERVAL_S = 0.0
    try:
        peer = b"\x42" * 32
        store.add_peer_negotiated(peer, 1 << 20)
        pid = b"\x09" * wire.PACKFILE_ID_LEN
        sid = shard_id(pid, 1)
        base = store.received_dir(peer)
        (base / "pack").mkdir(parents=True)
        (base / "shard").mkdir(parents=True)
        (base / "pack" / pid.hex()).write_bytes(b"p" * 700)
        (base / "shard" / sid.hex()).write_bytes(b"s" * 300)
        store.add_peer_received(peer, 1000)

        wants = [(wire.FileInfoKind.PACKFILE, pid),
                 (wire.FileInfoKind.SHARD, sid),
                 # unknown id: skipped, zero bytes, not an error
                 (wire.FileInfoKind.PACKFILE,
                  b"\x0a" * wire.PACKFILE_ID_LEN)]
        t = _FakeTransport([_reclaim_body(wants)])
        freed = loop.run_until_complete(node.serve_reclaim(peer, t))
        assert freed == 1000
        assert not (base / "pack" / pid.hex()).exists()
        assert not (base / "shard" / sid.hex()).exists()
        # the deleted bytes stopped counting against the requester
        assert store.get_peer(peer).bytes_received == 0
        ack, = t.sent
        assert ack.kind == wire.P2PBodyKind.RECLAIM_ACK
        assert ack.acked_sequence == 3 and ack.offset == 1000
        # idempotent re-delivery: already-gone files contribute zero
        t2 = _FakeTransport([_reclaim_body(wants)])
        assert loop.run_until_complete(node.serve_reclaim(peer, t2)) == 0
    finally:
        defaults.RECLAIM_MIN_INTERVAL_S = saved
        store.close()


def test_serve_reclaim_throttles_and_rejects_garbage(tmp_path, loop):
    node, store = _mk_node(tmp_path)
    saved = (defaults.RECLAIM_MIN_INTERVAL_S, defaults.RECLAIM_MAX_ITEMS)
    defaults.RECLAIM_MIN_INTERVAL_S = 0.0
    defaults.RECLAIM_MAX_ITEMS = 2
    try:
        peer = b"\x42" * 32
        store.add_peer_negotiated(peer, 1 << 20)
        # a non-reclaim body on a reclaim connection is a protocol error
        bad = wire.P2PBody(
            kind=wire.P2PBodyKind.REQUEST,
            header=wire.P2PHeader(sequence_number=1,
                                  session_nonce=b"\x00" * 16),
            request_type=wire.RequestType.TRANSPORT)
        with pytest.raises(P2PError):
            loop.run_until_complete(
                node.serve_reclaim(peer, _FakeTransport([bad])))
        # an oversized batch is refused before any disk work
        wants = [(wire.FileInfoKind.PACKFILE,
                  bytes([i]) * wire.PACKFILE_ID_LEN) for i in range(3)]
        with pytest.raises(P2PError):
            loop.run_until_complete(
                node.serve_reclaim(peer, _FakeTransport(
                    [_reclaim_body(wants)])))
        # rate limit: a hostile owner cannot spam deletes
        defaults.RECLAIM_MIN_INTERVAL_S = 60.0
        with pytest.raises(P2PError, match="throttled"):
            loop.run_until_complete(
                node.serve_reclaim(peer, _FakeTransport(
                    [_reclaim_body(wants[:1])])))
    finally:
        defaults.RECLAIM_MIN_INTERVAL_S, defaults.RECLAIM_MAX_ITEMS = saved
        store.close()


# --- run_gc on an offline engine --------------------------------------------


def _two_generation_world(engine, store):
    """Packfile A (both blobs dead after prune) + B (live); A placed on
    a fake holder.  Returns (pid_a, path_a, pid_b, hashes)."""
    pid_a, path_a, hashes_a = _write_packfile(
        engine._pack_dir(), [b"old-1" * 40, b"old-2" * 40])
    engine.index.finalize_packfile(pid_a, hashes_a)
    pid_b, _path_b, hashes_b = _write_packfile(
        engine._pack_dir(), [b"new-1" * 40])
    engine.index.finalize_packfile(pid_b, hashes_b)
    engine.index.flush()
    s1 = _snap(store, b"1", None, [b"old-1" * 40, b"old-2" * 40], now=100.0)
    _snap(store, b"2", s1, [b"new-1" * 40], now=200.0)
    store.record_placement(pid_a, b"\x42" * 32,
                           path_a.stat().st_size, shard_index=-1)
    return pid_a, path_a, pid_b, hashes_a + hashes_b


def test_run_gc_drops_dead_packfiles_offline(tmp_path, loop):
    engine, store = _mk_engine(tmp_path)
    try:
        pid_a, path_a, pid_b, hashes = _two_generation_world(engine, store)
        report = loop.run_until_complete(engine.run_gc("keep-last:1"))
        assert report["snapshots_pruned"] == 1
        assert report["packfiles_dropped"] == 1
        assert report["packfiles_compacted"] == 0
        assert report["blobs_dropped"] == 2
        assert report["bytes_reclaimed_remote"] > 0
        assert report["placements_retired"] == 1
        # node is None: the backlog row persists for the next drain
        assert report["reclaims_sent"] == 0
        assert [(f, p) for f, p, _k, _s in store.reclaim_backlog()] == \
            [(bytes(pid_a), b"\x42" * 32)]
        assert store.all_placements() == []
        assert not path_a.exists()
        assert engine.index.lookup(hashes[0]) is None
        assert engine.index.lookup(hashes[2]) == bytes(pid_b)
        # durable: a fresh index reload agrees (tombstones applied)
        fresh = BlobIndex(KEYS, store.index_dir())
        fresh.load()
        assert fresh.lookup(hashes[0]) is None
        assert bytes(pid_a) not in fresh.packfile_ids()
        assert store.get_gc_state() is None

        # a second pass finds nothing left to collect
        again = loop.run_until_complete(engine.run_gc("keep-last:1"))
        assert again["packfiles_dropped"] == 0
        assert again["blobs_dropped"] == 0
        snap = obs_metrics.registry().snapshot()
        runs = {s["labels"]["outcome"]: s["value"]
                for s in snap["bkw_gc_runs_total"]["series"]}
        assert runs == {"ok": 2}
    finally:
        store.close()


def test_run_gc_refuses_unmanifested_retained_snapshots(tmp_path, loop):
    engine, store = _mk_engine(tmp_path)
    try:
        # no snapshots at all: nothing restorable to reason about
        report = loop.run_until_complete(engine.run_gc())
        assert "no retained snapshots" in report["refused"]
        # a pre-lifecycle snapshot (lineage row, empty manifest): GC must
        # refuse rather than collect blobs it cannot prove dead
        store.record_snapshot(blake3_hash(b"legacy"), None, 10, [])
        pid, _path, hashes = _write_packfile(engine._pack_dir(), [b"x" * 64])
        engine.index.finalize_packfile(pid, hashes)
        engine.index.flush()
        report = loop.run_until_complete(engine.run_gc())
        assert "no manifest" in report["refused"]
        assert engine.index.lookup(hashes[0]) == bytes(pid)
    finally:
        store.close()


def test_gc_classify_and_compaction_internals(tmp_path, loop):
    """classify → stage (local-first) → repack → swap, offline.  The
    networked placement of the replacements is the scenario's job; here
    the sparse packfile's local copy feeds the repack directly."""
    engine, store = _mk_engine(tmp_path)
    try:
        live_payload, dead_payload = b"L" * 100, b"D" * 1000
        pid, path, hashes = _write_packfile(
            engine._pack_dir(), [live_payload, dead_payload])
        engine.index.finalize_packfile(pid, hashes)
        engine.index.flush()
        s1 = _snap(store, b"1", None, [live_payload, dead_payload],
                   now=100.0)
        _snap(store, b"2", s1, [live_payload], now=200.0)
        store.apply_retention("keep-last:1")

        live = store.live_blobs()
        drop, compact = engine._gc_classify(live, store.manifest_blobs())
        # 100 of 1100 known bytes live: under the occupancy threshold
        assert (drop, compact) == ([], [bytes(pid)])

        staging = store.data_base / "gc_staging"
        staged = loop.run_until_complete(
            engine._gc_stage_packfiles(compact, staging))
        assert staged == {bytes(pid): engine._pack_dir()}  # local-first

        new_map = engine._gc_repack(compact, staged, live)
        (npid, info), = new_map.items()
        assert info["hashes"] == [blake3_hash(live_payload)]
        # the replacement is sealed + audit-ready, but NOT yet in the
        # index: the swap is the one commit point
        assert packfile_path(engine._pack_dir(), npid).is_file()
        assert engine.challenge_tables.has(npid)
        assert npid not in engine.index.packfile_ids()

        swap = engine._gc_apply_swap(
            compact, {p: i["hashes"] for p, i in new_map.items()})
        assert swap["blobs_dropped"] == 1
        assert engine.index.lookup(blake3_hash(live_payload)) == bytes(npid)
        assert engine.index.lookup(blake3_hash(dead_payload)) is None
        assert not path.exists()
        assert not engine.challenge_tables.has(pid)
    finally:
        store.close()


# --- crash seams ------------------------------------------------------------


def test_gc_crash_at_swap_pre_rolls_back(tmp_path, loop, plane):
    engine, store = _mk_engine(tmp_path)
    try:
        pid_a, path_a, _pid_b, hashes = _two_generation_world(engine, store)
        plane.arm_crash("gc.swap.pre")
        with pytest.raises(faults.CrashInjected):
            loop.run_until_complete(engine.run_gc("keep-last:1"))
        # the sweep plan is durable, the index untouched
        assert store.get_gc_state()["phase"] == "place"
        assert path_a.exists()

        engine2, _ = Engine(KEYS, store, None, None), None
        engine2.auto_repair = False
        rep = loop.run_until_complete(engine2.recover())
        assert rep["gc_rolled_back"] == 1
        assert store.get_gc_state() is None
        # nothing committed: the old world is fully intact
        assert engine2.index.lookup(hashes[0]) == bytes(pid_a)
        assert len(store.all_placements()) == 1

        # the re-run converges, and recovery after it is a no-op
        report = loop.run_until_complete(engine2.run_gc("keep-last:1"))
        assert report["packfiles_dropped"] == 1
        assert engine2.index.lookup(hashes[0]) is None
        assert loop.run_until_complete(
            engine2.recover())["reconciled"] == 0
    finally:
        store.close()


def test_gc_crash_at_swap_post_rolls_forward(tmp_path, loop, plane):
    engine, store = _mk_engine(tmp_path)
    try:
        pid_a, path_a, _pid_b, hashes = _two_generation_world(engine, store)
        plane.arm_crash("gc.swap.post")
        with pytest.raises(faults.CrashInjected):
            loop.run_until_complete(engine.run_gc("keep-last:1"))
        # the swap committed before the crash: index flushed, locals gone
        assert store.get_gc_state()["phase"] == "reclaim"
        assert not path_a.exists()

        engine2 = Engine(KEYS, store, None, None)
        engine2.auto_repair = False
        rep = loop.run_until_complete(engine2.recover())
        assert rep["gc_rolled_forward"] == 1
        assert store.get_gc_state() is None
        assert engine2.index.lookup(hashes[0]) is None
        # the best-effort tail survives for the next drain
        assert len(store.reclaim_backlog()) == 1
        assert loop.run_until_complete(
            engine2.recover())["reconciled"] == 0
    finally:
        store.close()


def test_gc_crash_before_sweep_plan_leaves_no_state(tmp_path, loop, plane):
    engine, store = _mk_engine(tmp_path)
    try:
        pid_a, path_a, _pid_b, hashes = _two_generation_world(engine, store)
        plane.arm_crash("gc.sweep.pre")
        with pytest.raises(faults.CrashInjected):
            loop.run_until_complete(engine.run_gc("keep-last:1"))
        # the prune committed (it is its own sqlite transaction) but no
        # gc state was ever written: recovery has nothing to resolve
        assert store.get_gc_state() is None
        assert len(store.retained_snapshots()) == 1
        engine2 = Engine(KEYS, store, None, None)
        engine2.auto_repair = False
        rep = loop.run_until_complete(engine2.recover())
        assert rep["gc_rolled_back"] == 0
        assert rep["gc_rolled_forward"] == 0
        report = loop.run_until_complete(engine2.run_gc("keep-last:1"))
        assert report["packfiles_dropped"] == 1
        assert engine2.index.lookup(hashes[0]) is None
    finally:
        store.close()


def test_recover_drops_zombie_gc_replacements(tmp_path, loop):
    """A crash before the compaction seal (gc.compact.seal.pre) leaves
    repacked packfiles on disk that NO plan names.  Recovery must not
    adopt them — every blob is still owned by the original packfile, so
    adoption would double-place the bytes forever."""
    engine, store = _mk_engine(tmp_path)
    try:
        payload = b"owned" * 50
        pid, _path, hashes = _write_packfile(engine._pack_dir(), [payload])
        engine.index.finalize_packfile(pid, hashes)
        engine.index.flush()
        # the orphaned replacement: same blob, fresh pid, not in the index
        zpid, zpath, _ = _write_packfile(engine._pack_dir(), [payload])
        engine.challenge_tables.save(
            zpid, [ChallengeEntry(0, 16, b"\x01" * wire.AUDIT_NONCE_LEN,
                                  b"\x02" * 32)])
        assert bytes(zpid) != bytes(pid)

        rep = loop.run_until_complete(engine.recover())
        assert rep["gc_rolled_back"] == 1
        assert rep["packfiles_adopted"] == 0
        assert not zpath.exists()
        assert not engine.challenge_tables.has(zpid)
        # the original ownership is untouched
        assert engine.index.lookup(hashes[0]) == bytes(pid)
        assert loop.run_until_complete(engine.recover())["reconciled"] == 0
    finally:
        store.close()


def test_recover_rolls_forward_a_half_applied_swap(tmp_path, loop):
    """Crash inside the swap, after the index flush but before the
    bookkeeping: the freshly-loaded index names the replacement, so
    recovery re-runs the idempotent swap body to finish retiring."""
    engine, store = _mk_engine(tmp_path)
    try:
        live_payload, dead_payload = b"live" * 50, b"dead" * 50
        pid, path, hashes = _write_packfile(
            engine._pack_dir(), [live_payload, dead_payload])
        engine.index.finalize_packfile(pid, hashes)
        store.record_placement(pid, b"\x42" * 32,
                               path.stat().st_size, shard_index=-1)
        zpid, _zpath, zhashes = _write_packfile(
            engine._pack_dir(), [live_payload])
        # the commit point landed: the swap's forget -> finalize ->
        # tombstone -> flush all hit disk...
        engine.index.forget_packfiles([pid])
        engine.index.finalize_packfile(zpid, zhashes)
        engine.index.record_tombstones([blake3_hash(dead_payload)])
        engine.index.flush()
        # ...with the plan still naming the swap that was interrupted
        store.set_gc_state({
            "phase": "place", "drop": [], "compact": [bytes(pid).hex()],
            "new": {bytes(zpid).hex(): {
                "hashes": [h.hex() for h in zhashes],
                "size": 1}}})

        engine2 = Engine(KEYS, store, None, None)
        engine2.auto_repair = False
        rep = loop.run_until_complete(engine2.recover())
        assert rep["gc_rolled_forward"] == 1
        assert store.get_gc_state() is None
        assert engine2.index.lookup(blake3_hash(live_payload)) == bytes(zpid)
        assert engine2.index.lookup(blake3_hash(dead_payload)) is None
        assert not path.exists()
        assert store.all_placements() == []
        assert len(store.reclaim_backlog()) == 1
    finally:
        store.close()


# --- crash-site registry completeness (bkwlint BKW003) ----------------------


def test_crash_site_registry_is_exact_per_bkw003():
    """The AST rule supersedes the old grep sweep: every
    ``faults.crashpoint(<CONST>)`` call resolves through a
    ``register_crash_site`` literal, every registered seam has a call
    site, every durable commit has an adjacent crashpoint — and the
    statically enumerated registry matches the live one exactly (a
    drift in either direction means the crash matrix and the code
    disagree about where crashes can be injected)."""
    from backuwup_tpu.analysis import (load_graph, run_lint, LintConfig,
                                       static_crash_sites)
    repo = Path(backuwup_tpu.__file__).parent.parent
    graph = load_graph(repo / "backuwup_tpu")
    assert static_crash_sites(graph) == set(faults.crash_sites())
    cfg = LintConfig.for_repo(repo)
    cfg.rules = {"BKW003"}
    report = run_lint(cfg, graph)
    assert not report.findings, \
        "\n".join(f.render() for f in report.findings)
    assert not report.stale_baseline


# --- the durability-sweep janitor (satellite: TTL on the monitor loop) ------


def test_partial_janitor_rides_the_durability_sweep(tmp_path, loop):
    engine, store = _mk_engine(tmp_path)
    try:
        part = store.received_dir(b"\x11" * 32) / "partial"
        part.mkdir(parents=True, exist_ok=True)
        old = time.time() - defaults.PARTIAL_STORE_TTL_S - 60
        for name in ("ff00.bin", "ff00.json"):
            (part / name).write_bytes(b"{}")
            os.utime(part / name, (old, old))

        monitor = InvariantMonitor(store, index=engine.index)

        async def drive():
            task = asyncio.ensure_future(monitor.run(
                interval_s=0.01, janitor=engine.expire_partials))
            try:
                for _ in range(200):
                    if not any(part.iterdir()):
                        return True
                    await asyncio.sleep(0.01)
                return False
            finally:
                task.cancel()
                try:
                    await task
                except asyncio.CancelledError:
                    pass

        assert loop.run_until_complete(drive())
    finally:
        store.close()


# --- the scenario ------------------------------------------------------------


@pytest.mark.scenario
def test_gc_scenario_races_collection_against_backup_restore(tmp_path, loop):
    """GC vs concurrent backup + restore on the exclusivity lock, with
    retention pruning real dead bytes: zero durability-violation seconds
    while bytes are reclaimed on the holders, ending in a byte-identical
    restore."""
    from backuwup_tpu.scenario import builtin_scenarios, run_scenario

    card = loop.run_until_complete(
        run_scenario(builtin_scenarios()["gc"], tmp_path))
    assert card.passed, card.render()
    gates = {a.name: a.passed for a in card.assertions}
    assert gates["gc_completed"] and gates["gc_reclaimed_bytes"]
    assert gates["gc_holders_freed_bytes"]
    assert card.invariants["violation_seconds"] == 0
    assert card.invariants["final"]["status"] == "ok"


@pytest.mark.scenario
@pytest.mark.slow
def test_gc_scenario_full_seam_matrix(tmp_path, loop):
    """Every GC commit seam armed in sequence; each crash must recover
    idempotently (the recovery_clean gate) with zero violations."""
    from backuwup_tpu.scenario import builtin_scenarios, run_scenario

    card = loop.run_until_complete(
        run_scenario(builtin_scenarios()["gc_full"], tmp_path))
    assert card.passed, card.render()
