"""Per-peer transfer estimators (net/peer_stats.py).

EWMA semantics (first-sample seeding, failures moving only the success
ratio), convergence under fault-plane-injected latency through the real
TransferScheduler, and persistence of the estimator rows across a
client restart (Store close + reopen).
"""

import asyncio

import pytest

from backuwup_tpu import defaults
from backuwup_tpu.net.p2p import P2PError
from backuwup_tpu.net.peer_stats import PeerStats, peer_label
from backuwup_tpu.net.transfer import TransferResult, TransferScheduler
from backuwup_tpu.obs import metrics as obs_metrics
from backuwup_tpu.store import PeerStatsRow, Store
from backuwup_tpu.utils import faults

pytestmark = pytest.mark.concurrency

PEER = b"\x11" * 32


@pytest.fixture
def plane():
    p = faults.install(faults.FaultPlane(seed=77))
    yield p
    faults.uninstall()


def _ok(size=1 << 20, send_s=0.1, wait_s=0.0):
    return TransferResult(PEER, size, True, wait_s=wait_s, send_s=send_s)


def _fail(size=1 << 20, send_s=0.05):
    return TransferResult(PEER, size, False,
                          error=P2PError("injected"), send_s=send_s)


def test_first_sample_seeds_the_estimators():
    ps = PeerStats(alpha=0.2)
    est = ps.observe(_ok(size=2 << 20, send_s=0.5), now=100.0)
    # seeded, not averaged against the zero prior
    assert est.throughput_bps == (2 << 20) / 0.5
    assert est.latency_s == 0.5
    assert est.success == 1.0
    assert est.samples == 1
    assert est.updated == 100.0
    assert ps.get(PEER) == est
    assert ps.get(b"\x22" * 32) is None


def test_ewma_moves_by_alpha():
    ps = PeerStats(alpha=0.5)
    ps.observe(_ok(size=1000, send_s=1.0))  # seed: 1000 B/s, 1.0 s
    est = ps.observe(_ok(size=3000, send_s=1.0))  # sample: 3000 B/s
    assert est.throughput_bps == pytest.approx(2000.0)
    assert est.latency_s == pytest.approx(1.0)
    assert est.samples == 2


def test_failures_move_success_but_not_rates():
    ps = PeerStats(alpha=0.5)
    seed = ps.observe(_ok(size=1000, send_s=1.0))
    est = ps.observe(_fail())
    # reliability decays, capacity knowledge is untouched
    assert est.success == pytest.approx(0.5)
    assert est.throughput_bps == seed.throughput_bps
    assert est.latency_s == seed.latency_s
    # a failure-first peer still seeds its rates on the first success
    ps2 = PeerStats(alpha=0.5)
    ps2.observe(_fail())
    est2 = ps2.observe(_ok(size=1000, send_s=1.0))
    assert est2.throughput_bps == pytest.approx(1000.0)
    assert est2.success == pytest.approx(0.5)


def test_convergence_under_fault_plane_latency(plane):
    """Real TransferScheduler + injected 80 ms per-send latency: after a
    stripe's worth of transfers the latency EWMA must sit right on the
    injected floor and the samples counter must match exactly."""
    plane.latency = 1.0  # every send draws the sleep
    plane.latency_s = 0.08
    ps = PeerStats(alpha=0.3)
    sched = TransferScheduler(peer_stats=ps)
    size = 64 * 1024

    async def send():
        await faults.PLANE.on_send(PEER)

    async def go():
        tasks = [sched.submit(PEER, size, send, label=f"s{i}")
                 for i in range(8)]
        return await TransferScheduler.gather(tasks)

    loop = asyncio.new_event_loop()
    try:
        results = loop.run_until_complete(asyncio.wait_for(go(), 30))
    finally:
        loop.close()
    assert all(r.ok for r in results)
    est = ps.get(PEER)
    assert est.samples == 8
    assert est.success == pytest.approx(1.0)
    # every sample's send_s >= the injected floor, so the EWMA is too;
    # loopback overhead stays well under one extra latency window
    assert 0.08 <= est.latency_s < 0.16
    assert 0 < est.throughput_bps <= size / 0.08
    # the per-peer histograms saw every transfer
    label = peer_label(PEER)
    sends = obs_metrics.registry().get("bkw_peer_transfer_send_seconds")
    assert sends.sum_value(peer=label) >= 8 * 0.08


def test_estimators_persist_across_client_restart(tmp_path):
    store = Store(directory=tmp_path / "cfg", data_base=tmp_path / "data")
    ps = PeerStats(store, alpha=0.2)
    ps.observe(_ok(size=1 << 20, send_s=0.1), now=50.0)
    ps.observe(_ok(size=1 << 20, send_s=0.3), now=60.0)
    ps.observe(_fail(), now=70.0)
    before = ps.get(PEER)
    store.close()

    # the restart: fresh Store handle, fresh estimator bank
    store2 = Store(directory=tmp_path / "cfg", data_base=tmp_path / "data")
    try:
        ps2 = PeerStats(store2, alpha=0.2)
        after = ps2.get(PEER)
        assert after is not None
        assert after.samples == 3
        assert after.throughput_bps == pytest.approx(before.throughput_bps)
        assert after.latency_s == pytest.approx(before.latency_s)
        assert after.success == pytest.approx(before.success)
        assert after.updated == pytest.approx(70.0)
        # loading re-exported the gauges for the restarted process
        label = peer_label(PEER)
        tput = obs_metrics.registry().get(
            "bkw_peer_throughput_bytes_per_second")
        assert tput.value(peer=label) == pytest.approx(
            before.throughput_bps)
        # and the bank keeps evolving from the persisted state
        evolved = ps2.observe(_ok(size=1 << 20, send_s=0.1), now=80.0)
        assert evolved.samples == 4
        row = store2.get_peer_stats(PEER)
        assert row is not None and row.samples == 4
    finally:
        store2.close()


def test_row_round_trip_and_upsert(tmp_path):
    store = Store(directory=tmp_path / "cfg", data_base=tmp_path / "data")
    try:
        assert store.get_peer_stats(PEER) is None
        assert store.all_peer_stats() == []
        store.put_peer_stats(PeerStatsRow(
            peer=PEER, throughput_bps=1e6, latency_s=0.2,
            success=0.9, samples=5, updated=123.0))
        store.put_peer_stats(PeerStatsRow(
            peer=PEER, throughput_bps=2e6, latency_s=0.1,
            success=0.95, samples=6, updated=124.0))
        rows = store.all_peer_stats()
        assert len(rows) == 1  # upsert, not append
        assert rows[0].throughput_bps == 2e6
        assert rows[0].samples == 6
    finally:
        store.close()


def test_default_alpha_comes_from_defaults():
    assert PeerStats().alpha == defaults.PEER_STATS_ALPHA
    assert 0.0 < defaults.PEER_STATS_ALPHA < 1.0
