# Coordination-server container (client image built from the same base).
#
# Mirrors the reference's server image (/root/reference/Dockerfile:1-25)
# in spirit: a slim runtime with only what `python -m backuwup_tpu
# server` needs.  The server's compute path is pure asyncio + SQLite —
# no JAX and no accelerator required — so the image installs only
# aiohttp + cryptography + numpy.  The CLIENT, whose dedup pipeline
# wants an accelerator, normally runs on the host against a real TPU; a
# CPU-only client container (native-C fast path) can be started from the
# same image with `BKW_ROLE=client`.

FROM python:3.12-slim AS runtime
ARG ROLE=server
WORKDIR /app

# gcc/make: the client role's native C fast path builds at first use;
# libzstd powers packfile compression (ctypes binding, no pip package)
RUN apt-get update && apt-get install -y --no-install-recommends \
    gcc make libzstd1 zstd && rm -rf /var/lib/apt/lists/*

RUN pip install --no-cache-dir aiohttp cryptography numpy websockets

COPY backuwup_tpu /app/backuwup_tpu
# the check role (BKW_ROLE=check) lints the shipped tree in place:
# the catalog + baseline ride along so the gate sees what CI sees
COPY docs/observability.md /app/docs/observability.md
COPY .bkwlint-baseline.json /app/.bkwlint-baseline.json

ENV BKW_ROLE=${ROLE}
ENV SERVER_BIND=0.0.0.0:9999
ENV SERVER_DB=/data/server.db
VOLUME /data
EXPOSE 9999

# server: coordination server on :9999 (TLS via TLS_CERT_FILE/TLS_KEY_FILE)
# client: set BKW_ROLE=client, SERVER_ADDR, CONFIG_DIR=/data and pass
#         e.g. `--backup-path /backup`
COPY docker-entrypoint.sh /app/docker-entrypoint.sh
ENTRYPOINT ["/bin/sh", "/app/docker-entrypoint.sh"]
